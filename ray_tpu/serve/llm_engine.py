"""Continuous-batching LLM inference engine for TPU.

The TPU-native heart of the Serve equivalent.  The reference has no
in-tree inference engine (models are user torch code inside replicas;
ray: python/ray/serve/_private/replica.py just invokes the callable) —
on TPU the engine must own the device loop, because XLA wants static
shapes and hates per-request recompiles.  Design:

  * a fixed number of KV-cache **slots** (the batch dimension of every
    compiled program) — requests claim a slot, decode advances ALL
    active slots in one jitted step (MXU stays batched);
  * **bucketed prefill**: prompts are right-padded to power-of-two
    buckets, one compile per bucket, causality hides the padding;
  * sampling happens **on device** (greedy or temperature), so the only
    per-step host transfer is one int32 per slot;
  * admission interleaves with decode: a new request prefills between
    decode steps and joins the running batch (continuous batching à la
    Orca; cf. PAPERS.md paged/ragged attention).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import queue
import threading
import time
from collections import deque
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.core.exceptions import PreemptedError, ShedError
from ray_tpu.serve import audit as _audit
from ray_tpu.serve import request_events as _reqev
from ray_tpu.util import tracing

log = logging.getLogger(__name__)

_TELEMETRY = None

# A decode step slower than this many times its running median is a
# stall worth shouting about (BENCH_r05's 1.14B collapse showed p95
# TTFT 200x p50 with no engine-side signal of WHERE time went).
STALL_FACTOR = 5.0


def _telemetry():
    """Engine metric singletons (created on first engine construction,
    re-registered on later fetches so a test's registry clear() cannot
    silently drop the serving plane from /metrics)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "ttft": metrics.Histogram(
                "raytpu_serve_ttft_seconds",
                "Time from submit to first generated token, per request.",
                boundaries=[0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                            1.0, 2.5, 5.0, 10.0, 30.0],
            ),
            "tpot": metrics.Histogram(
                "raytpu_serve_tpot_seconds",
                "Mean per-output-token latency after the first token, "
                "per request.",
                boundaries=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                            0.05, 0.1, 0.25, 1.0],
            ),
            "queue_depth": metrics.Gauge(
                "raytpu_serve_queue_depth",
                "Requests admitted nowhere yet: waiting queue + paged "
                "backlog, sampled at dispatch time.",
            ),
            "batch_size": metrics.Histogram(
                "raytpu_serve_decode_batch_size",
                "Active slots per decode dispatch (continuous-batch "
                "occupancy).",
                boundaries=[1, 2, 4, 8, 16, 32, 64],
            ),
            "step_wall": metrics.Gauge(
                "raytpu_serve_step_wall_seconds",
                "High-water mark of per-decode-step wall time "
                "(dispatch-to-fetch wall of a chunk / steps in it — an "
                "upper bound on device step time including pipeline "
                "queueing).",
            ),
            "queue_age": metrics.Gauge(
                "raytpu_serve_admission_queue_age_seconds",
                "Age of the oldest request still waiting for admission "
                "(waiting queue + paged backlog), sampled at dispatch "
                "time.  Climbing age with stable depth = stalled "
                "admission, not load.",
            ),
            "itl": metrics.Histogram(
                "raytpu_serve_request_itl_seconds",
                "Worst client-observed inter-token gap within a "
                "finished request (the hiccup a streaming reader "
                "actually sees; mean gap is TPOT).  A speculative "
                "verify round emits several tokens in one burst: the "
                "round's wall gap is divided by the burst size so the "
                "histogram stays an exact per-token partition.",
                boundaries=[0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                            0.1, 0.25, 1.0, 5.0],
            ),
            "spec_rounds": metrics.Counter(
                "raytpu_serve_spec_rounds_total",
                "Speculative verify rounds completed (one draft+verify "
                "cycle of up to spec_k tokens per round).",
            ),
            "spec_drafted": metrics.Counter(
                "raytpu_serve_spec_drafted_tokens_total",
                "Tokens drafted by the draft model across verify "
                "rounds.",
            ),
            "spec_accepted": metrics.Counter(
                "raytpu_serve_spec_accepted_tokens_total",
                "Drafted tokens the target model accepted (the free "
                "bonus token each round emits on top is not counted).",
            ),
            "spec_accept_ratio": metrics.Gauge(
                "raytpu_serve_spec_accept_ratio",
                "Cumulative accepted/drafted token ratio over this "
                "engine's speculative verify rounds.",
            ),
            "slo": metrics.Counter(
                "raytpu_serve_request_slo_total",
                "Terminal requests by SLO outcome: met only when the "
                "request FINISHED inside every bound of "
                "EngineConfig.slo (no slo config = every finish is "
                "met); failed/cancelled always miss.",
                tag_keys=("outcome",),
            ),
            "terminal": metrics.Counter(
                "raytpu_serve_request_terminal_total",
                "Requests reaching a terminal state, by state "
                "(FINISHED / FAILED / CANCELLED / SHED).",
                tag_keys=("state",),
            ),
            "arrived": metrics.Counter(
                "raytpu_serve_requests_arrived_total",
                "Requests submitted to this engine (admitted, shed or "
                "rejected alike) — the raw arrival process.  Its rate "
                "and slope are the LEADING load signal: they move "
                "before the queue forms, which is what predictive "
                "autoscaling (reason arrival_slope) keys on.",
            ),
            "shed": metrics.Counter(
                "raytpu_serve_shed_total",
                "Requests refused at admission because the queue was "
                "already older than the SLO budget "
                "(EngineConfig.shed_queue_age_s) — clean fast-fail "
                "backpressure instead of a guaranteed-late answer.",
            ),
            "goodput": metrics.Gauge(
                "raytpu_serve_goodput_ratio",
                "Tokens from SLO-met requests over all tokens of "
                "terminal requests — goodput vs raw throughput.",
            ),
            "step_tokens": metrics.Counter(
                "raytpu_serve_step_tokens_total",
                "Tokens dispatched to the device, split by phase "
                "(prefill vs decode).  Attributes step wall time: a "
                "rising prefill share explains decode-stream TPOT "
                "regressions without any per-request change.",
                tag_keys=("phase",),
            ),
            "kv_pages_free": metrics.Gauge(
                "raytpu_serve_kv_pages_free",
                "Free pages in the paged KV pool (neither slot-mapped "
                "nor held by the prefix cache).",
            ),
            "kv_pages_cached": metrics.Gauge(
                "raytpu_serve_kv_pages_cached",
                "Pages owned by the prefix cache (0 when the cache is "
                "disabled).  free + cached + slot-owned = pool.",
            ),
            "prefix_requests": metrics.Counter(
                "raytpu_serve_prefix_requests_total",
                "Admitted requests by prefix-cache outcome (hit = at "
                "least one full page reused).",
                tag_keys=("outcome",),
            ),
            "prefix_hit_ratio": metrics.Gauge(
                "raytpu_serve_prefix_hit_ratio",
                "Cumulative prompt tokens served from the prefix cache "
                "over all prompt tokens admitted (token-weighted hit "
                "ratio).",
            ),
            "prefix_hit_depth": metrics.Histogram(
                "raytpu_serve_prefix_hit_depth_tokens",
                "Per-request prefix-cache hit depth in tokens (0 = "
                "cold prefill) — joins with TTFT for "
                "TTFT-by-hit-depth.",
                boundaries=[1, 16, 32, 64, 128, 256, 512, 1024, 2048,
                            4096],
            ),
            "prefix_cached_pages": metrics.Gauge(
                "raytpu_serve_prefix_cached_pages",
                "Pages currently held by the radix-tree prefix index.",
            ),
            "prefix_evicted": metrics.Counter(
                "raytpu_serve_prefix_evicted_pages_total",
                "Cache pages evicted (refcount-0 LRU) under admission "
                "pressure.",
            ),
            "collective_bytes": metrics.Counter(
                "raytpu_serve_collective_bytes_total",
                "Bytes one shard puts on the wire for decode-step "
                "allreduces, by link class (ici = in-host exact psum, "
                "dcn = cross-daemon leg, int8-quantized unless the "
                "bf16 fallback is configured).  Analytic accounting "
                "(parallel.collectives.allreduce_wire_bytes) so CPU "
                "emulation and real DCN report the same number.",
                tag_keys=("link",),
            ),
            "collective_seconds": metrics.Histogram(
                "raytpu_serve_collective_seconds",
                "Measured wall time of one decode-shaped collective "
                "per link class, observed from startup calibration "
                "probes (the per-step collective inside the fused "
                "decode program is not separately observable from the "
                "host).",
                boundaries=[1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
                            1e-3, 2.5e-3, 5e-3, 1e-2, 5e-2, 0.25, 1.0],
                tag_keys=("link",),
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    # The migration/disagg families (serve/kv_transfer) register with
    # the engine so `check_metrics --require` sees them at zero before
    # any page ever moves.
    from ray_tpu.serve import kv_transfer as _kvt

    # The adapter-pool families (serve/adapter_pool) merge the same way
    # so `check_metrics --require` pins them at zero even on engines
    # that never load an adapter.
    from ray_tpu.serve import adapter_pool as _apool

    # The waterfall-attribution + flight-recorder families merge the
    # same way so the tier-1 --require pins see them at zero on engines
    # that never missed an SLO.
    from ray_tpu.serve import latency_attribution as _lat
    from ray_tpu.util import flight_recorder as _frec

    # The doctor families (util/doctor) merge the same way so the
    # tier-1 --require pins see them at zero before any audit runs.
    from ray_tpu.util import doctor as _doc

    out = dict(_TELEMETRY)
    out.update(_kvt._telemetry())
    out.update(_apool._telemetry())
    out.update(_lat._telemetry())
    out.update({f"frec_{k}": v for k, v in _frec._telemetry().items()})
    out.update({f"doctor_{k}": v for k, v in _doc._telemetry().items()})
    return out


@dataclasses.dataclass(frozen=True)
class SLO:
    """Per-request latency objectives; a None bound is unconstrained.
    A request is SLO-met only when it FINISHED inside every set bound —
    failed and cancelled requests always miss, which is what makes the
    goodput gauge honest under churn."""

    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    e2e_s: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_slots: int = 8
    max_seq_len: int = 1024
    min_prefill_bucket: int = 32
    max_new_tokens_default: int = 128
    eos_id: Optional[int] = None
    # Paged KV cache (block tables over a page pool — TPU PagedAttention,
    # ops/paged_attention.py).  num_pages=0 sizes the pool for full
    # occupancy (slots × max_seq_len); smaller pools oversubscribe and
    # requests queue when no pages are free.
    page_size: int = 64
    num_pages: int = 0
    # Decode this many steps per host round-trip (lax.scan on device).
    # Amortizes host↔device latency; tokens past an EOS inside a chunk
    # are discarded host-side.  Chunk sizes: powers of two ≤ this.
    decode_chunk: int = 16
    # Chunked prefill (paged mode): prompts longer than this many
    # tokens prefill in segments of this size, interleaved with decode
    # chunks — a long prompt never stalls running streams for its full
    # prefill (0 = always one-shot).
    prefill_chunk: int = 0
    # Latency objectives driving the SLO met/missed counters and the
    # goodput gauge (None = every finished request counts as met).
    slo: Optional[SLO] = None
    # Overload shedding: refuse (ShedError) new submissions while the
    # oldest unadmitted request has already waited longer than this —
    # a request queued behind it could only produce a guaranteed-late
    # answer, so fail fast and immediately-retriable instead of
    # timing the client out.  The natural setting is the e2e SLO
    # budget (slo.e2e_s).  None = never shed.
    shed_queue_age_s: Optional[float] = None
    # Ragged batching (paged mode): one unified device step per
    # dispatch mixing decode rows (1 token per active slot) with
    # prefill chunks from the admission queue, packed up to
    # token_budget tokens (ops/ragged_paged_attention.py).  Replaces
    # the prefill-vs-decode interleave — a long prompt streams in
    # budget-sized chunks beside live decode rows instead of stalling
    # them.  token_budget=0 sizes it max_slots + max(prefill_chunk,
    # page_size).
    ragged_batching: bool = False
    token_budget: int = 0
    # Radix-tree prefix cache over the page pool
    # (serve/prefix_index.py): finished requests donate their full KV
    # pages to a refcounted trie; admission matches the longest cached
    # prefix and schedules the ragged prefill from the hit depth
    # instead of token 0.  Requires ragged_batching (prefill-from-
    # offset rides the per-row `start` descriptor of the unified
    # step).  Shared pages are copy-on-write: the only write that can
    # land in one — the last-token re-run of an exact full-prompt hit
    # — splits the page first.  Eviction is refcount-0 LRU, driven by
    # admission pressure so cached pages never starve new requests.
    prefix_cache: bool = False
    # Multi-tenant LoRA multiplexing (serve/adapter_pool.py): sizing of
    # the paged adapter-weight pool backing requests that carry an
    # adapter_id.  Only consulted when the model config enables LoRA
    # (LlamaConfig(lora=...) routes llama_paged_adapter to build a
    # pool + segmented ragged step).  adapter_pool_pages=0 auto-sizes
    # (room for 4 resident adapters); max_batch_adapters bounds the
    # DISTINCT adapters one ragged step can gather (incl. the null
    # row); adapter_int8 stores pool pages int8 with per-page scales.
    adapter_pool_pages: int = 0
    adapter_page_elems: int = 8192
    max_batch_adapters: int = 8
    adapter_int8: bool = False
    # Speculative decoding (requires ragged_batching): each round the
    # engine drafts spec_k tokens autoregressively on a small draft
    # model (LLMEngine(draft_params=..., draft_adapter=...); omitted =
    # self-draft with the target weights — a testing/calibration mode)
    # and verifies all of them in ONE target step by packing them as a
    # k-token prefill-chunk row of the ragged batch, accepting the
    # longest matching prefix plus one free token from the target
    # logits.  Rejection rewinds the slot's host length mirror to the
    # accept boundary — the paged KV rollback; rejected tail positions
    # are overwritten by later steps and never become
    # prefix-cache-visible.  The scheduler gates speculation per round:
    # only greedy base-model rows with no in-flight tokens speculate,
    # never while prefill chunks contend for the token budget, and a
    # cold acceptance EMA (< spec_cold_accept) pauses speculation for
    # spec_cooldown_rounds dispatches before re-probing.  Draft KV
    # lives in a second paged pool of spec_draft_pages pages (0 =
    # full-occupancy auto-sizing) under the same allocator discipline.
    spec_decode: bool = False
    spec_k: int = 4
    spec_draft_pages: int = 0
    spec_cold_accept: float = 0.2
    spec_cooldown_rounds: int = 32

    def buckets(self) -> List[int]:
        out, b = [], self.min_prefill_bucket
        while b < self.max_seq_len:
            out.append(b)
            b *= 2
        out.append(self.max_seq_len)
        return out


@dataclasses.dataclass(frozen=True)
class EngineAdapter:
    """Model plug: how the engine talks to a model family.

    init_cache(slots, max_len) -> cache pytree with int32 "length"[slots]
    prefill_slot(params, tokens[S], true_len, slot, cache) -> (logits[V], cache)
    decode_slots(params, tokens[slots], active[slots], cache) -> (logits[slots,V], cache)
    """

    init_cache: Callable[[int, int], Any]
    prefill_slot: Callable[..., Tuple[jax.Array, Any]]
    decode_slots: Callable[..., Tuple[jax.Array, Any]]
    # Optional batched admission: prefill_batch(params, tokens[K,S],
    # true_lens[K], slots[K], cache) -> (logits[K,V], cache).  One
    # [K, S] forward instead of K sequential rows — the MXU-friendly
    # shape; the engine falls back to a fori_loop of prefill_slot when
    # absent.
    prefill_batch: Optional[Callable[..., Tuple[jax.Array, Any]]] = None


def llama_adapter(cfg) -> EngineAdapter:
    from ray_tpu.models import llama

    return EngineAdapter(
        init_cache=lambda slots, max_len: llama.init_kv_cache(
            cfg, slots, max_len
        ),
        prefill_slot=lambda params, tokens, true_len, slot, cache:
            llama.prefill_slot(params, tokens, true_len, slot, cfg, cache),
        decode_slots=lambda params, tokens, active, cache:
            llama.decode_slots(params, tokens, active, cfg, cache),
        prefill_batch=lambda params, tokens, true_lens, slots, cache:
            llama.prefill_batch(params, tokens, true_lens, slots, cfg,
                                cache),
    )


@dataclasses.dataclass(frozen=True)
class PagedEngineAdapter:
    """Model plug for the paged (block-table) cache:

    init_cache(num_pages, page_size) -> pytree (no length field; the
        engine tracks lengths host-side)
    prefill_slot(params, tokens[S], true_len, pages[S/page], cache)
        -> (logits[V], cache)
    decode_slots(params, tokens[slots], active, block_tables, lengths,
        cache) -> (logits[slots, V], cache, new_lengths)
    """

    init_cache: Callable[[int, int], Any]
    prefill_slot: Callable[..., Tuple[jax.Array, Any]]
    decode_slots: Callable[..., Tuple[jax.Array, Any, jax.Array]]
    # Batched admission over page rows (see EngineAdapter.prefill_batch).
    prefill_batch: Optional[Callable[..., Tuple[jax.Array, Any]]] = None
    # Incremental prefill: prefill_chunk(params, tokens[K,C], start[K],
    # chunk_lens[K], pages_rows[K,maxp], cache) -> (logits[K,V], cache)
    # — enables EngineConfig.prefill_chunk.
    prefill_chunk: Optional[Callable[..., Tuple[jax.Array, Any]]] = None
    # Unified ragged step: ragged_step(params, tokens[T], tok_pos[T],
    # row_slot[R], row_start[R], row_len[R], row_off[R], block_tables,
    # cache) -> (logits[R,V], cache).  One device program serving a
    # mixed batch of decode rows (len 1) and prefill chunks — enables
    # EngineConfig.ragged_batching.
    ragged_step: Optional[Callable[..., Tuple[jax.Array, Any]]] = None
    # COW split for the prefix cache: copy_page(cache, src, dst) ->
    # cache duplicates ONE physical page (all layers, k+v and any
    # per-page quantization scales) so a writer can diverge from a
    # shared page — enables EngineConfig.prefix_cache.
    copy_page: Optional[Callable[..., Any]] = None
    # Tensor-parallel serving (LLMEngine(mesh=...)): shard_params
    # places params on the mesh (pass HOST arrays for big models — the
    # transfer shards directly, never materializing an unsharded copy
    # on one device); cache_shardings(mesh) returns the sharding tree
    # matching init_cache's output so the engine can ALLOCATE the page
    # pool under it.  GSPMD partitions the jitted programs from these
    # placements; the model's decode attention runs per shard (llama:
    # cfg.tensor_parallel + paged_decode_attention_tp).
    shard_params: Optional[Callable[[Any, Any], Any]] = None
    cache_shardings: Optional[Callable[[Any], Any]] = None
    # Multi-host shard groups: collective_step_bytes(mesh, rows) ->
    # {"ici": bytes, "dcn": bytes} — analytic per-device wire bytes of
    # ONE decode step over ``rows`` active slots, feeding
    # raytpu_serve_collective_bytes_total.  collective_probes(mesh) ->
    # {link: zero-arg callable} running one decode-shaped collective;
    # the engine times them at startup for
    # raytpu_serve_collective_seconds.
    collective_step_bytes: Optional[
        Callable[[Any, int], Dict[str, int]]] = None
    collective_probes: Optional[
        Callable[[Any], Dict[str, Callable]]] = None
    # Multi-tenant LoRA multiplexing: ragged_step_lora(params, tokens,
    # tok_pos, row_slot, row_start, row_len, row_off, block_tables,
    # cache, pool, page_table, tok_adapter) -> (logits[R,V], cache) —
    # the unified step with per-token segmented adapter deltas
    # (ops/segmented_lora) gathered from the paged pool.
    # make_adapter_pool(EngineConfig) builds the pool the engine owns
    # (serve/adapter_pool.AdapterPool); both set iff the model config
    # enables LoRA.
    ragged_step_lora: Optional[Callable[..., Tuple[jax.Array, Any]]] = None
    make_adapter_pool: Optional[Callable[[Any], Any]] = None
    # Speculative decoding: ragged_step_verify(params, tokens, tok_pos,
    # row_slot, row_start, row_len, row_off, block_tables, cache,
    # logit_idx) -> (logits[R,V], verify_logits[Tv,V], cache) — the
    # unified step returning EXTRA logits at the flat-buffer positions
    # in logit_idx (each verify row's k+1 candidate tokens), with the
    # first R row logits bit-identical to ragged_step.  The LoRA
    # variant threads the adapter-pool args the same way so verify
    # rows can ride a mixed-adapter batch — enables
    # EngineConfig.spec_decode.
    ragged_step_verify: Optional[
        Callable[..., Tuple[jax.Array, jax.Array, Any]]] = None
    ragged_step_lora_verify: Optional[
        Callable[..., Tuple[jax.Array, jax.Array, Any]]] = None


def llama_paged_adapter(cfg, lora_loader=None) -> PagedEngineAdapter:
    """``lora_loader`` (adapter_id -> factor pytree / flat vector)
    feeds the adapter pool when cfg.lora is set; None uses the
    deterministic seeded loader (segmented_lora.default_adapter_loader),
    which every replica resolves identically — the property adapter
    failover relies on."""
    from ray_tpu.models import llama

    lora_fields: Dict[str, Any] = {}
    if getattr(cfg, "lora", None) is not None:
        from ray_tpu.ops import segmented_lora as _sl
        from ray_tpu.serve.adapter_pool import AdapterPool

        def ragged_step_lora(params, tokens, tok_pos, row_slot, row_start,
                             row_len, row_off, bt, cache, pool, page_table,
                             tok_adapter):
            flat = _sl.gather_adapter_flat(pool, page_table)
            stacks = _sl.gather_adapter_stacks(flat, cfg, cfg.lora)
            return llama.ragged_step_paged(
                params, tokens, tok_pos, row_slot, row_start, row_len,
                row_off, bt, cfg, cache,
                lora=(stacks, tok_adapter, cfg.lora.scale))

        def ragged_step_lora_verify(params, tokens, tok_pos, row_slot,
                                    row_start, row_len, row_off, bt,
                                    cache, pool, page_table, tok_adapter,
                                    logit_idx):
            flat = _sl.gather_adapter_flat(pool, page_table)
            stacks = _sl.gather_adapter_stacks(flat, cfg, cfg.lora)
            return llama.ragged_step_paged(
                params, tokens, tok_pos, row_slot, row_start, row_len,
                row_off, bt, cfg, cache,
                lora=(stacks, tok_adapter, cfg.lora.scale),
                logit_idx=logit_idx)

        lora_fields = {
            "ragged_step_lora": ragged_step_lora,
            "ragged_step_lora_verify": ragged_step_lora_verify,
            "make_adapter_pool": lambda ecfg: AdapterPool(
                cfg, cfg.lora,
                num_pages=ecfg.adapter_pool_pages,
                page_elems=ecfg.adapter_page_elems,
                max_batch_adapters=ecfg.max_batch_adapters,
                int8=ecfg.adapter_int8,
                loader=lora_loader),
        }

    return PagedEngineAdapter(
        **lora_fields,
        init_cache=lambda num_pages, page: llama.init_paged_cache(
            cfg, num_pages, page
        ),
        prefill_slot=lambda params, tokens, true_len, pages, cache:
            llama.prefill_slot_paged(params, tokens, true_len, pages,
                                     cfg, cache),
        decode_slots=lambda params, tokens, active, bt, lens, cache:
            llama.decode_slots_paged(params, tokens, active, bt, lens,
                                     cfg, cache),
        prefill_batch=lambda params, tokens, true_lens, pages_rows, cache:
            llama.prefill_batch_paged(params, tokens, true_lens,
                                      pages_rows, cfg, cache),
        prefill_chunk=lambda params, tokens, start, chunk_lens, pages_rows,
        cache:
            llama.prefill_chunk_paged(params, tokens, start, chunk_lens,
                                      pages_rows, cfg, cache),
        ragged_step=lambda params, tokens, tok_pos, row_slot, row_start,
        row_len, row_off, bt, cache:
            llama.ragged_step_paged(params, tokens, tok_pos, row_slot,
                                    row_start, row_len, row_off, bt, cfg,
                                    cache),
        ragged_step_verify=lambda params, tokens, tok_pos, row_slot,
        row_start, row_len, row_off, bt, cache, logit_idx:
            llama.ragged_step_paged(params, tokens, tok_pos, row_slot,
                                    row_start, row_len, row_off, bt, cfg,
                                    cache, logit_idx=logit_idx),
        copy_page=llama.copy_page_paged,
        shard_params=lambda params, mesh:
            llama.shard_params_for_serving(params, cfg, mesh),
        cache_shardings=lambda mesh: llama.paged_cache_shardings(
            mesh, kv_int8=cfg.kv_int8),
        collective_step_bytes=lambda mesh, rows:
            llama.decode_collective_bytes(cfg, mesh, rows),
        collective_probes=lambda mesh:
            llama.serving_collective_probes(cfg, mesh),
    )


def _sample(logits: jax.Array, temperature: jax.Array,
            key: jax.Array) -> jax.Array:
    """logits [..., V], temperature broadcastable — greedy at temp 0,
    categorical otherwise; computed on device."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temperature, 1e-6)[..., None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


@dataclasses.dataclass
class Request:
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    stream: "queue.Queue"
    req_id: int
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    # Telemetry: the submitter's span context (None when tracing is
    # off) and the prefill-dispatch stamp splitting queue wait from
    # prefill in the request's span tree.
    trace_ctx: Optional[Dict[str, str]] = None
    admitted_at: Optional[float] = None
    # End-to-end id labeling the ring, spans, and log lines (minted at
    # the serve router, or locally when submitted straight to the
    # engine); incremental inter-token-gap tracking rides _emit.
    request_id: str = ""
    last_token_at: Optional[float] = None
    max_itl_s: float = 0.0
    # Prompt tokens served from the prefix cache (0 = cold prefill);
    # stamped at admission, mirrored to the request ring so
    # TTFT-by-hit-depth is observable downstream.
    prefix_hit: int = 0
    # Multi-tenant multiplexing: the LoRA adapter this request decodes
    # under ("" = base model).  Rides the ring rows and the per-row
    # descriptor of the ragged step.
    adapter_id: str = ""
    # Speculative decoding: tokens this request drafted / had accepted
    # across its verify rounds (0/0 = never speculated).  Mirrored to
    # the ring as the `spec` column of `raytpu list requests`.
    spec_drafted: int = 0
    spec_accepted: int = 0

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


_DONE = object()


class CompletionStream:
    """Client view of one request: iterate tokens as they generate."""

    def __init__(self, req: Request, engine: "Optional[LLMEngine]" = None):
        self._req = req
        self._engine = engine
        self._done = threading.Event()

    @property
    def request_id(self) -> str:
        return self._req.request_id

    def cancel(self) -> None:
        """Ask the engine to cancel this request (idempotent; a no-op
        once the request is terminal).  The stream still ends with its
        normal _DONE marker — tokens emitted before the cancel took
        effect stay delivered."""
        if self._engine is not None:
            self._engine.cancel(self._req.request_id)

    def __iter__(self):
        while not self._done.is_set():
            item = self._req.stream.get()
            if item is _DONE:
                self._done.set()
                return
            if isinstance(item, BaseException):
                self._done.set()
                raise item
            yield item

    def result(self, timeout_s: Optional[float] = None) -> List[int]:
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        while not self._done.is_set():
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                item = self._req.stream.get(timeout=remaining)
            except queue.Empty:
                raise TimeoutError(
                    f"generation not finished within {timeout_s}s "
                    f"({len(self._req.tokens)} tokens so far)"
                ) from None
            if item is _DONE:
                self._done.set()
            elif isinstance(item, BaseException):
                self._done.set()
                raise item
        return list(self._req.tokens)

    @property
    def metrics(self) -> Dict[str, Any]:
        r = self._req
        return {
            "ttft_s": r.ttft_s,
            "total_s": (None if r.finished_at is None
                        else r.finished_at - r.submitted_at),
            "num_tokens": len(r.tokens),
        }


class LLMServer:
    """Ready-made Serve deployment hosting an LLMEngine.

    Request payload: {"tokens": [...], "max_new_tokens"?: int,
    "temperature"?: float} → {"tokens": [...], "metrics": {...}}.
    Use with ``serve.deployment(...)(LLMServer).bind(cfg, engine_cfg,
    param_loader)`` — param_loader runs inside the replica so weights
    never travel through the object store.
    """

    def __init__(self, model_cfg: Any, engine_cfg: EngineConfig,
                 param_loader: Callable[[], Any], *, adapter_factory:
                 Callable[[Any], EngineAdapter] = None,
                 draft_param_loader: Callable[[], Any] = None,
                 draft_model_cfg: Any = None):
        # Rank 0 of a shard group (serve/shard_group.py) hosts the
        # engine over a hybrid DCN×ICI serving mesh: weights
        # tensor-parallel over tp (in host) × dcn_tp (across group
        # members), KV pools sharded along heads, decode's DCN
        # allreduce legs int8-quantized unless the group configured
        # the bf16 fallback.
        from ray_tpu.serve.shard_group import current_shard_group

        sg = current_shard_group()
        mesh = None
        if sg is not None:
            import dataclasses as _dc

            from ray_tpu.parallel.mesh import create_serving_mesh

            model_cfg = _dc.replace(
                model_cfg, tensor_parallel=True,
                dcn_quantized_allreduce=sg.quantized)
            mesh = create_serving_mesh(sg.size, sg.tensor_parallel)
        # Disaggregated prefill/decode role (serve/kv_transfer),
        # installed by the hosting ReplicaActor the same way the shard
        # group is.  Roles need the prefix trie: migrated pages are
        # identified and resumed through its chained path hashes.
        from ray_tpu.serve.kv_transfer import current_disagg

        self._disagg = current_disagg()
        if (self._disagg is not None
                and self._disagg.role != "unified"
                and not engine_cfg.prefix_cache):
            raise ValueError(
                "disaggregated serving roles require "
                "EngineConfig.prefix_cache=True (KV migration is keyed "
                "by the prefix trie's chained path hashes)")
        # Replica-local mirrors of the disagg counters: replicas run as
        # separate actor processes, so tests and the state API read
        # these through disagg_stats() instead of scraping the
        # replica's own Prometheus registry.
        self._handoff_counts = {"migrated": 0, "failed": 0, "local": 0}
        self._disagg_requests = 0
        # Round-robin fallback for handoff-target spreading when a
        # payload carries no request id to hash.
        self._handoff_rr = itertools.count()
        make_adapter = adapter_factory or (
            llama_paged_adapter if mesh is not None else llama_adapter)
        # Speculative decoding's draft model loads inside the replica
        # like the target (weights never cross the object store).  No
        # loader + spec_decode=True = the engine self-drafts.
        draft_params = (draft_param_loader()
                        if draft_param_loader is not None else None)
        draft_adapter = None
        if draft_params is not None:
            draft_adapter = make_adapter(draft_model_cfg
                                         if draft_model_cfg is not None
                                         else model_cfg)
        self.engine = LLMEngine(
            param_loader(), make_adapter(model_cfg), engine_cfg,
            mesh=mesh, draft_params=draft_params,
            draft_adapter=draft_adapter,
        )

    @staticmethod
    def _adapter_id(payload: Dict[str, Any]) -> str:
        """The request's LoRA adapter id: explicit payload key > the
        multiplexed model id the replica installed from request
        metadata (handle.options(multiplexed_model_id=...) -> router
        metadata -> serve/multiplex contextvar) > "" (base model)."""
        from ray_tpu.serve import multiplex as _mux

        return (payload.get("adapter_id")
                or _mux.get_multiplexed_model_id() or "")

    def __call__(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        # Explicit payload id > the id the replica installed from
        # request metadata (the router-minted one) > engine-local mint.
        stream = self.engine.submit(
            payload["tokens"],
            max_new_tokens=payload.get("max_new_tokens"),
            temperature=payload.get("temperature", 0.0),
            request_id=payload.get("request_id"),
            adapter_id=self._adapter_id(payload),
        )
        tokens = stream.result()
        return {"tokens": tokens, "metrics": stream.metrics,
                "request_id": stream.request_id}

    def stream(self, payload: Dict[str, Any]):
        """Streaming entry (serve data plane, ``stream=True`` handles):
        yields tokens as the engine generates them.  A preemption
        surfaces as PreemptedError AFTER every already-generated token
        has been yielded, so the router's failover knows the exact
        delivered prefix.

        On a prefill-role replica a fresh request runs the handoff
        protocol instead: prefill + the first handoff_after_tokens
        tokens here, migrate the KV pages to a decode replica, then
        raise MigrationHandoff so the client generator resumes the
        stream there (the migrated prefix is a cache hit — no
        recompute).  ANY transfer failure degrades to a plain
        PreemptedError: the PR-5 continuation replay recomputes
        locally, the stream never stalls."""
        dis = self._disagg
        if dis is not None and dis.role != "unified":
            from ray_tpu.serve.kv_transfer import _telemetry as _kvt_tm

            _kvt_tm()["disagg_requests"].inc(tags={"role": dis.role})
            self._disagg_requests += 1
            if (dis.role == "prefill"
                    and not payload.get("_disagg_resumed")):
                yield from self._stream_prefill_handoff(payload)
                return
        stream = self.engine.submit(
            payload["tokens"],
            max_new_tokens=payload.get("max_new_tokens"),
            temperature=payload.get("temperature", 0.0),
            request_id=payload.get("request_id"),
            adapter_id=self._adapter_id(payload),
        )
        for tok in stream:
            yield tok

    def _pick_decode_target(self, request_id: Optional[str] = None):
        """(replica_id, handle) of one RUNNING decode-role replica of
        this deployment, or None (controller gone, none running, …) —
        checked BEFORE the truncated local submit so a missing target
        degrades to unified serving, not a wasted handoff.

        Least-loaded first: the controller returns each candidate's
        last-pushed num_ongoing_requests next to its handle, so
        handoffs chase live decode capacity instead of hashing blindly
        across a fleet whose load the census order knows nothing
        about.  The request-id hash only breaks ties between
        equally-loaded candidates (deterministic per request, so
        concurrent retries of one handoff agree); payloads without an
        id round-robin the tie instead."""
        import zlib

        from ray_tpu.core import api
        from ray_tpu.serve.controller import CONTROLLER_NAME

        dis = self._disagg
        try:
            controller = api.get_actor(CONTROLLER_NAME)
            rows = api.get(controller.migration_targets.remote(
                dis.app_name, dis.deployment_name, role="decode",
                exclude=[dis.replica_id], with_load=True), timeout=2.0)
        except Exception:
            return None
        if not rows:
            return None
        low = min(row[2] for row in rows)
        best = [row for row in rows if row[2] <= low]
        if request_id:
            idx = zlib.crc32(str(request_id).encode()) % len(best)
        else:
            idx = next(self._handoff_rr) % len(best)
        return best[idx][0], best[idx][1]

    def _stream_prefill_handoff(self, payload: Dict[str, Any]):
        from ray_tpu.core import api
        from ray_tpu.serve import kv_transfer as _kvt

        dis = self._disagg
        tm = _kvt._telemetry()
        requested = payload.get("max_new_tokens")
        if requested is None:
            requested = self.engine.config.max_new_tokens_default
        target = self._pick_decode_target(payload.get("request_id"))
        if target is None or requested <= dis.handoff_after_tokens:
            # No decode replica (yet) or nothing left to hand off:
            # serve unified locally rather than stall.
            tm["disagg_handoffs"].inc(tags={"outcome": "local"})
            self._handoff_counts["local"] += 1
            stream = self.engine.submit(
                payload["tokens"],
                max_new_tokens=requested,
                temperature=payload.get("temperature", 0.0),
                request_id=payload.get("request_id"),
                adapter_id=self._adapter_id(payload),
            )
            for tok in stream:
                yield tok
            return
        # Phase 1: prefill + first tokens locally.  The request
        # FINISHES here, so its prompt pages land in the prefix trie
        # (the finish path donates full pages) — exactly what the
        # lease below pins and exports.
        stream = self.engine.submit(
            payload["tokens"],
            max_new_tokens=dis.handoff_after_tokens,
            temperature=payload.get("temperature", 0.0),
            request_id=payload.get("request_id"),
            adapter_id=self._adapter_id(payload),
        )
        delivered: List[int] = []
        for tok in stream:
            delivered.append(tok)
            yield tok
        # The stream may have finished NATURALLY inside phase 1 (eos or
        # the max_seq_len cap within the first handoff_after_tokens
        # tokens).  Handing off anyway would resume it on the decode
        # replica and generate past the finish — outputs must stay
        # byte-identical to unified serving, so end the stream here.
        eos_id = self.engine.config.eos_id
        if (len(delivered) < dis.handoff_after_tokens
                or (eos_id is not None and delivered
                    and int(delivered[-1]) == eos_id)
                or (len(payload["tokens"]) + len(delivered)
                    >= self.engine.config.max_seq_len)):
            tm["disagg_handoffs"].inc(tags={"outcome": "local"})
            self._handoff_counts["local"] += 1
            return
        # Phase 2: migrate the request's cached pages to the target.
        target_id, handle = target
        seq = list(payload["tokens"]) + [int(t) for t in delivered]
        mig_tokens = seq[:max(len(seq) - 1, 0)]
        budget = dis.migration_timeout_s
        migrated = False
        try:
            lease = self.engine.migration_lease(mig_tokens,
                                                timeout_s=budget)
            if lease is not None:
                try:
                    transfer = self.engine.migration_export(
                        lease["lease_id"], mode=dis.transfer,
                        timeout_s=budget)
                    ref = handle.handle_request.remote(
                        "ingest_kv_transfer", (transfer,), {}, None)
                    api.get(ref, timeout=budget)
                    migrated = True
                finally:
                    self.engine.migration_release(lease["lease_id"],
                                                  timeout_s=budget)
        except Exception as e:
            log.warning("kv migration to %s failed (%r): falling back "
                        "to local recompute", target_id, e)
        continuation = {"prompt": list(payload["tokens"]),
                        "tokens": list(delivered),
                        "temperature": payload.get("temperature", 0.0),
                        "request_id": payload.get("request_id"),
                        "adapter_id": self._adapter_id(payload)}
        if migrated:
            tm["disagg_handoffs"].inc(tags={"outcome": "migrated"})
            self._handoff_counts["migrated"] += 1
            raise _kvt.MigrationHandoff(
                "prefill finished: KV pages migrated, resume on the "
                "decode replica", continuation=continuation,
                target_replica_id=target_id)
        tm["disagg_handoffs"].inc(tags={"outcome": "failed"})
        self._handoff_counts["failed"] += 1
        raise PreemptedError(
            "kv migration failed: resume via local recompute",
            continuation=continuation)

    def disagg_stats(self) -> Dict[str, Any]:
        """Replica-local disaggregation counters (role, requests
        entering under a role, handoff outcomes, migration traffic) —
        the RPC-readable mirror of the raytpu_serve_disagg_* and
        raytpu_serve_kv_migration_* families."""
        dis = self._disagg
        return {
            "role": dis.role if dis is not None else "unified",
            "requests": self._disagg_requests,
            "handoffs": dict(self._handoff_counts),
            "kv_migration": self.engine.stats().get("kv_migration", {}),
        }

    def ingest_kv_transfer(self, transfer: Dict[str, Any]) -> int:
        """Replica-to-replica RPC target: land one migration transfer
        in this engine's pool.  Returns pages ingested."""
        return self.engine.migration_ingest(transfer)

    def export_hot_prefixes(self, max_pages: int = 256,
                            mode: str = "int8") -> List[Dict[str, Any]]:
        """Replica-to-replica RPC target: serialize this engine's hot
        cached prefixes (prefix migration, source side)."""
        return self.engine.export_hot_prefixes(max_pages=max_pages,
                                               mode=mode)

    def pull_prefix_cache(self, max_pages: int = 256, *,
                          app_name: Optional[str] = None,
                          deployment_name: Optional[str] = None,
                          replica_id: Optional[str] = None,
                          transfer: Optional[str] = None,
                          timeout_s: Optional[float] = None) -> int:
        """Prefix migration, destination side: pull hot prefixes from
        the warmest peer replica (longest published prefix summary)
        into the local pool instead of recomputing them.  Returns pages
        ingested; 0 when there is no peer or nothing to pull.

        Identity normally comes from the ambient disagg context; the
        explicit keyword identity is the autoscaler's warm-start path —
        the controller knows who the new replica is and calls this on
        it right after it reaches RUNNING, so a scaled-up group starts
        with the fleet's hot prefixes instead of a cold trie."""
        from ray_tpu.core import api
        from ray_tpu.serve.controller import CONTROLLER_NAME

        dis = self._disagg
        if dis is not None:
            app_name = app_name or dis.app_name
            deployment_name = deployment_name or dis.deployment_name
            replica_id = replica_id or dis.replica_id
            transfer = transfer or dis.transfer
            if timeout_s is None:
                timeout_s = dis.migration_timeout_s
        transfer = transfer or "int8"
        timeout_s = 5.0 if timeout_s is None else timeout_s
        if (self.engine._prefix is None
                or not (app_name and deployment_name and replica_id)):
            return 0
        try:
            controller = api.get_actor(CONTROLLER_NAME)
            rows = api.get(controller.migration_targets.remote(
                app_name, deployment_name, role=None,
                exclude=[replica_id], with_summary=True),
                timeout=2.0)
        except Exception:
            return 0
        rows = [r for r in rows if r[2]]  # peers with a summary
        if not rows:
            return 0
        # Warmest peer = most published path hashes.
        rows.sort(key=lambda r: (-len(r[2].get("hashes", ())), r[0]))
        _, handle, _ = rows[0]
        try:
            transfers = api.get(handle.handle_request.remote(
                "export_hot_prefixes", (max_pages, transfer),
                {}, None), timeout=timeout_s)
        except Exception:
            return 0
        total = 0
        for transfer in transfers:
            try:
                total += self.engine.migration_ingest(transfer)
            except Exception as e:
                log.warning("prefix-migration ingest failed: %r", e)
        return total

    def drain(self, grace_s: float = 5.0) -> int:
        """Preemption notice: drain the engine (stop admitting, evict
        long requests with continuations).  Called by the replica's
        drain path."""
        return self.engine.drain(grace_s)

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def pressure(self) -> Dict[str, Any]:
        """SLO-pressure signals for the autoscaling policy, polled by
        the hosting ReplicaActor's metrics push loop next to
        num_ongoing_requests: the engine's admission-queue age (the
        leading overload signal), cumulative goodput ratio (the
        trailing guard; None until a request reaches a terminal state)
        and cumulative arrival count (the predictive signal — its
        slope moves before any queue forms)."""
        return {"queue_age_s": self.engine.admission_queue_age(),
                "goodput": self.engine.goodput_ratio(),
                "arrivals": self.engine.arrivals_total()}

    def prefix_summary(self) -> Optional[Dict[str, Any]]:
        """Prefix-cache routing summary (None when the cache is off).
        The hosting ReplicaActor polls this and pushes changes to the
        controller for cache-aware routing."""
        return self.engine.prefix_summary()

    def adapter_summary(self) -> Optional[Dict[str, Any]]:
        """Resident-adapter routing summary (None when LoRA
        multiplexing is off).  The hosting ReplicaActor polls this and
        pushes changes to the controller for adapter-affinity
        routing — the same path prefix_summary rides."""
        return self.engine.adapter_summary()

    def doctor(self, deep: bool = True) -> Dict[str, Any]:
        """Run one invariant audit over the hosted engine and return
        its report — the per-replica RPC target behind the
        controller's doctor() fan-out (``GET /api/v0/doctor`` /
        ``raytpu doctor --deep``)."""
        return self.engine.doctor(deep=deep)

    def check_health(self) -> None:
        if self.engine._stopped.is_set():
            raise RuntimeError("engine stopped")
        # A critical invariant violation (a corrupted page partition /
        # refcount) from the most recent audit fails the health
        # verdict: the controller restarts a replica whose KV pool can
        # silently corrupt streams.  Leaks and census drift (error /
        # warning) alert through metrics instead of a restart.
        critical = self.engine._auditor.last_critical()
        if critical:
            v = critical[0]
            raise RuntimeError(
                f"doctor: invariant {v['check']} violated "
                f"({v['subject']}: expected {v['expected']!r}, got "
                f"{v['actual']!r}; {len(critical)} critical total)")


_ENGINE_IDS = itertools.count()


class LLMEngine:
    """Continuous-batching scheduler around jitted prefill/decode."""

    def __init__(self, params: Any, adapter: EngineAdapter,
                 config: EngineConfig, *, seed: int = 0, mesh: Any = None,
                 draft_params: Any = None,
                 draft_adapter: Optional["PagedEngineAdapter"] = None):
        self.config = config
        self.adapter = adapter
        self._params = params
        self._paged = isinstance(adapter, PagedEngineAdapter)
        # Speculative decoding is armed by _init_spec at the end of the
        # ragged setup; every other mode must still see the flag.
        self._spec_on = False
        # Tensor-parallel serving: engine state lives sharded over the
        # mesh; GSPMD partitions every program from the placements and
        # the model's decode attention runs per shard (parity: serving
        # a model bigger than one chip — SURVEY §7 phase 7).
        self._mesh = mesh
        if mesh is not None and not self._paged:
            raise ValueError("mesh-sharded serving requires the paged "
                             "adapter (PagedEngineAdapter)")
        if mesh is not None and adapter.shard_params is not None:
            self._params = params = adapter.shard_params(params, mesh)
        if self._paged:
            page = config.page_size
            self._maxp = -(-config.max_seq_len // page)
            self._num_pages = (config.num_pages
                               or config.max_slots * self._maxp)
            if mesh is not None and adapter.cache_shardings is not None:
                # Allocate the pool directly under its shardings: a
                # materialize-then-reshard would briefly hold the WHOLE
                # unsharded pool on one device — an OOM at exactly the
                # model sizes tp serving exists for.
                self._cache = jax.jit(
                    partial(adapter.init_cache, self._num_pages, page),
                    out_shardings=adapter.cache_shardings(mesh),
                )()
            else:
                self._cache = adapter.init_cache(self._num_pages, page)
            if (isinstance(self._cache, dict)
                    and "k_scale" in self._cache
                    and config.prefill_chunk > 0
                    and not config.ragged_batching):
                # The ragged path appends through a page-granular
                # one-hot gather that CAN grow page scales, so int8 KV
                # + chunked prompts is only a restriction of the legacy
                # interleave.
                raise ValueError(
                    "kv_int8 pools do not support chunked prefill "
                    "(per-token page scatters cannot grow page scales "
                    "on the gather path) — set "
                    "EngineConfig.prefill_chunk=0, enable "
                    "ragged_batching, or serve with bf16 KV")
            self._free_pages = list(range(self._num_pages))
            self._slot_pages: Dict[int, List[int]] = {}
            # Unallocated block-table entries hold the OOB sentinel
            # (num_pages): a stale slot decoded past its allocation by
            # an overshooting in-flight chunk then scatters out of
            # bounds (mode="drop") instead of corrupting page 0.
            self._bt = np.full((config.max_slots, self._maxp),
                               self._num_pages, np.int32)
            self._lens = np.zeros((config.max_slots,), np.int32)
            self._backlog: List[Request] = []  # admitted-but-no-pages
            # Radix-tree prefix cache (EngineConfig.prefix_cache):
            # finished requests donate full pages to the trie; slots
            # borrow them at admission (_slot_borrowed tracks which
            # block-table entries are cache-owned so release never
            # returns them to the free list).
            self._prefix = None
            self._slot_borrowed: Dict[int, List[int]] = {}
            if config.prefix_cache:
                if not config.ragged_batching:
                    raise ValueError(
                        "prefix_cache requires ragged_batching=True "
                        "(prefill-from-offset rides the ragged step's "
                        "per-row start descriptor)")
                from ray_tpu.serve.prefix_index import PrefixIndex
                self._prefix = PrefixIndex(page)
            self._prefix_hit_tokens = 0
            self._prefix_prompt_tokens = 0
        else:
            if config.prefix_cache:
                raise ValueError(
                    "prefix_cache requires the paged adapter "
                    "(PagedEngineAdapter) — the cache indexes KV pages")
            self._prefix = None
            self._cache = adapter.init_cache(config.max_slots,
                                             config.max_seq_len)
        # KV page-migration plane (serve/kv_transfer): clients enqueue
        # lease/export/ingest ops here and the LOOP thread services
        # them (_process_migrations) — the cache is donated between
        # jitted dispatches, so only the loop may touch it.
        self._mig_lock = threading.Lock()
        self._mig_ops: List[Dict[str, Any]] = []
        self._mig_leases: Dict[str, Dict[str, Any]] = {}
        self._mig_lease_ids = itertools.count(1)
        self._mig_counts = {"pages_out": 0, "pages_in": 0,
                            "bytes_out": 0, "bytes_in": 0}
        self._waiting: "queue.Queue[Request]" = queue.Queue()
        self._slot_req: Dict[int, Request] = {}
        self._free_slots = list(range(config.max_slots))
        # Last sampled token per slot lives ON DEVICE: the next decode
        # chunk reads it without a host round trip, which is what lets
        # chunk N+1 dispatch before chunk N's tokens reach the host
        # (the depth-2 pipeline that hides the dispatch RTT).
        self._cur_dev = jnp.zeros((config.max_slots,), jnp.int32)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            self._cur_dev = jax.device_put(
                self._cur_dev, NamedSharding(mesh, PartitionSpec()))
        self._temps = np.zeros((config.max_slots,), np.float32)
        # In-flight entries (prefill/decode) ride a dedicated FETCH
        # thread: the engine loop dispatches device work and emits
        # fetched tokens, while the fetcher turns queued entries into
        # ONE batched device_get at a time (a get costs a full ~100 ms
        # round trip on tunneled devices regardless of payload, so the
        # batch size self-balances to the arrival rate).
        self._fetchq: "queue.Queue" = queue.Queue()
        self._fetched: "queue.Queue" = queue.Queue()
        self._unprocessed = 0  # dispatched entries not yet emitted
        self._inflight_tokens: Dict[int, int] = {}  # slot → undelivered
        self._req_counter = itertools.count()
        # Cumulative arrival count (every submit, shed included) —
        # mirrored by the arrived counter; kept as a plain int so
        # pressure() reads it without touching the registry.
        self._arrived = 0
        self._stopped = threading.Event()
        # Preemption-aware drain (see drain()): _draining stops
        # admission, _drain_evict tells the loop to preempt whatever is
        # still in flight.  Both are one-way latches.
        self._draining = threading.Event()
        self._drain_evict = threading.Event()
        self._preempted_count = 0
        self._work = threading.Event()
        self._steps = 0
        self._tokens_out = 0
        self._tm = _telemetry()
        # Multi-host shard groups: per-step collective byte accounting
        # + one-time timed calibration probes (see PagedEngineAdapter).
        self._coll_bytes_fn = None
        if (mesh is not None and self._paged
                and adapter.collective_step_bytes is not None):
            self._coll_bytes_fn = partial(
                adapter.collective_step_bytes, mesh)
        if (mesh is not None and self._paged
                and adapter.collective_probes is not None):
            self._calibrate_collectives(adapter.collective_probes(mesh))
        self._update_page_gauges()
        # Request-lifecycle ring (util/state.list_requests, dashboard
        # /api/v0/requests, timeline request rows all read it).  The
        # engine holds the only strong ref; the module registry is weak.
        self._engine_id = f"engine-{next(_ENGINE_IDS)}"
        self._ring = _reqev.RequestEventBuffer(self._engine_id)
        _reqev.register(self._ring)
        # Invariant audit plane (serve/audit + util/doctor): the
        # auditor runs O(slots) conservation checks between dispatches
        # and full partition walks on demand / idle / drain / stop.
        # doctor() enqueues audit ops exactly like the cancel and
        # migration queues — the loop owns all audited state.
        self._auditor = _audit.EngineAuditor(self)
        self._audit_lock = threading.Lock()
        self._audit_ops: List[Dict[str, Any]] = []
        self._crashed = False
        self._drain_audited = False
        _audit.register_engine(self)
        # Cancellation handoff: client threads drop ids here; the
        # engine loop resolves them against its registries between
        # dispatches (the loop owns all slot/page state).
        self._cancel_lock = threading.Lock()
        self._cancels: set = set()
        # Goodput accounting: tokens from SLO-met requests vs all
        # tokens of terminal requests.
        self._good_tokens = 0
        self._terminal_tokens = 0
        self._step_walls: deque = deque(maxlen=64)  # recent s/step
        self._step_wall_hw = 0.0  # watermark mirrored to the gauge
        self._stall_events = 0  # steps past STALL_FACTOR x median
        self._xprof_recorded: set = set()  # programs already registered

        slots = config.max_slots

        # NOTE on host↔device traffic: on tunneled/remote devices a
        # sync round trip costs ~100 ms and even jax.random.split is a
        # dispatched program — so every per-chunk side op here is folded
        # INTO the jitted programs (keys derive from an int seed inside
        # jit; the next-token vector and the updated cur come back as
        # extra outputs), and token fetches are deferred + batched.

        @partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
        def prefill_batch_fn(k, params, cache, tokens, true_lens,
                             slot_or_pages, temps, seed, cur, slot_ids):
            """Prefill k slots in ONE dispatch (k static: {1,2,4,8}).
            Rows are sequential inside the program (each writes its own
            slot); padding rows are copies of the last real row — an
            idempotent rewrite whose sample is discarded.  Also scatters
            the sampled first tokens into the device-resident cur."""
            keys = jax.random.split(jax.random.key(seed[0]), k)

            def body(i, carry):
                cache, toks = carry
                logits, cache = adapter.prefill_slot(
                    params, tokens[i], true_lens[i], slot_or_pages[i], cache
                )
                tok = _sample(logits[None, :], temps[i][None], keys[i])[0]
                return cache, toks.at[i].set(tok)

            cache, toks = jax.lax.fori_loop(
                0, k, body, (cache, jnp.zeros((k,), jnp.int32))
            )
            # Padding rows carry an OOB scatter id (mode="drop"): with
            # temperature > 0 they sample a DIFFERENT token for the
            # same slot, and the scatter must not let a padding row's
            # sample beat the emitted real-row token.
            return cache, toks, cur.at[slot_ids].set(toks, mode="drop")

        @partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
        def decode_fn(n_steps, params, cache, cur, active, temps, seed):
            def step(carry, k):
                cache, cur = carry
                logits, cache = adapter.decode_slots(params, cur, active, cache)
                toks = _sample(logits, temps, k)
                toks = jnp.where(active, toks, cur)
                return (cache, toks), toks

            keys = jax.random.split(jax.random.key(seed[0]), n_steps)
            (cache, cur), toks = jax.lax.scan(step, (cache, cur), keys)
            return cache, toks, cur, None  # [n_steps, slots]

        @partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
        def decode_paged_fn(n_steps, params, cache, cur, active, temps,
                            seed, bt, lens):
            def step(carry, k):
                cache, cur, lens = carry
                logits, cache, lens = adapter.decode_slots(
                    params, cur, active, bt, lens, cache
                )
                toks = _sample(logits, temps, k)
                toks = jnp.where(active, toks, cur)
                return (cache, toks, lens), toks

            keys = jax.random.split(jax.random.key(seed[0]), n_steps)
            (cache, cur, lens), toks = jax.lax.scan(
                step, (cache, cur, lens), keys
            )
            # cur + lens ride back as DEVICE arrays: the next dispatch
            # feeds them straight in — no host round trip.
            return cache, toks, cur, lens

        if self._paged and adapter.prefill_chunk is not None:
            @partial(jax.jit, donate_argnums=(1,))
            def prefill_chunk_fn(params, cache, tokens, start, chunk_lens,
                                 pages_rows, temps, seed, cur, slot_ids):
                logits, cache = adapter.prefill_chunk(
                    params, tokens, start, chunk_lens, pages_rows, cache
                )
                toks = _sample(logits, temps, jax.random.key(seed[0]))
                return cache, toks, cur.at[slot_ids].set(toks,
                                                         mode="drop")

            self._prefill_chunk_fn = prefill_chunk_fn
        else:
            self._prefill_chunk_fn = None
        # Ragged batching: ONE jitted program per scheduler step, fed a
        # packed token buffer of decode rows + prefill chunks.  Static
        # (T, R) = (token_budget, max_slots) → a single compile serves
        # every mix.
        self._ragged = bool(config.ragged_batching)
        if self._ragged:
            if not self._paged or adapter.ragged_step is None:
                raise ValueError(
                    "EngineConfig.ragged_batching requires a "
                    "PagedEngineAdapter with ragged_step")
            if mesh is not None:
                raise ValueError(
                    "ragged_batching does not support mesh-sharded "
                    "serving yet — drop mesh= or ragged_batching")
            self._token_budget = config.token_budget or (
                config.max_slots
                + max(config.prefill_chunk, config.page_size))
            if self._token_budget < config.max_slots + 1:
                raise ValueError(
                    "token_budget must leave room for a prefill chunk "
                    f"beside {config.max_slots} decode rows")

            @partial(jax.jit, donate_argnums=(1,))
            def ragged_step_fn(params, cache, host_toks, decode_mask,
                               tok_slot, tok_pos, row_slot, row_start,
                               row_len, row_off, temps, seed, cur,
                               scatter_ids, bt):
                # Decode rows read their token from the device-resident
                # cur (no host round trip — same pipelining contract as
                # decode_paged_fn); prefill rows carry host tokens.
                toks = jnp.where(decode_mask, cur[tok_slot], host_toks)
                logits, cache = adapter.ragged_step(
                    params, toks, tok_pos, row_slot, row_start, row_len,
                    row_off, bt, cache)
                sampled = _sample(logits, temps,
                                  jax.random.key(seed[0]))
                # Mid-chunk prefill rows and padding rows carry OOB
                # scatter ids: their sample is meaningless and must not
                # clobber a live slot's cur.
                cur = cur.at[scatter_ids].set(sampled, mode="drop")
                return cache, sampled, cur

            self._ragged_step_fn = ragged_step_fn

            # Multi-tenant LoRA multiplexing: the engine owns the paged
            # adapter pool and a LoRA variant of the ragged program
            # (pool + gather plan + per-token adapter index as extra
            # args).  The pool array is NOT donated — the host manager
            # mutates it on loads, not the step.  Batches with no
            # adapter rows keep dispatching the base program above, so
            # adapter-off traffic pays zero overhead.
            if adapter.make_adapter_pool is not None:
                if adapter.ragged_step_lora is None:
                    raise ValueError(
                        "adapter exposes make_adapter_pool without "
                        "ragged_step_lora")
                self._adapters = adapter.make_adapter_pool(config)

                @partial(jax.jit, donate_argnums=(1,))
                def ragged_step_lora_fn(params, cache, host_toks,
                                        decode_mask, tok_slot, tok_pos,
                                        row_slot, row_start, row_len,
                                        row_off, temps, seed, cur,
                                        scatter_ids, bt, pool,
                                        page_table, tok_adapter):
                    toks = jnp.where(decode_mask, cur[tok_slot],
                                     host_toks)
                    logits, cache = adapter.ragged_step_lora(
                        params, toks, tok_pos, row_slot, row_start,
                        row_len, row_off, bt, cache, pool, page_table,
                        tok_adapter)
                    sampled = _sample(logits, temps,
                                      jax.random.key(seed[0]))
                    cur = cur.at[scatter_ids].set(sampled, mode="drop")
                    return cache, sampled, cur

                self._ragged_step_lora_fn = ragged_step_lora_fn
            else:
                self._adapters = None
                self._ragged_step_lora_fn = None

            if self._prefix is not None:
                if adapter.copy_page is None:
                    raise ValueError(
                        "prefix_cache requires an adapter with "
                        "copy_page (the COW split of a shared page)")

                @partial(jax.jit, donate_argnums=(0,))
                def copy_page_fn(cache, src, dst):
                    return adapter.copy_page(cache, src, dst)

                self._copy_page_fn = copy_page_fn

                # Migration gather/scatter (serve/kv_transfer).  Page
                # ids are padded to a power of two (fill = the OOB
                # scratch page) to bound recompiles; the gather's
                # padding rows are sliced off on the host, the
                # scatter's padding rows write zeros into the scratch
                # page, where nothing can read them.
                @jax.jit
                def mig_gather_fn(cache, ids):
                    out = {"k": cache["k"][:, :, ids],
                           "v": cache["v"][:, :, ids]}
                    if "k_scale" in cache:
                        out["k_scale"] = cache["k_scale"][:, ids]
                        out["v_scale"] = cache["v_scale"][:, ids]
                    return out

                @partial(jax.jit, donate_argnums=(0,))
                def mig_scatter_fn(cache, ids, payload):
                    out = dict(cache)
                    for key in ("k", "v"):
                        out[key] = cache[key].at[:, :, ids].set(
                            payload[key])
                    for key in ("k_scale", "v_scale"):
                        if key in cache:
                            out[key] = cache[key].at[:, ids].set(
                                payload[key])
                    return out

                self._mig_gather_fn = mig_gather_fn
                self._mig_scatter_fn = mig_scatter_fn
            if config.spec_decode:
                self._init_spec(draft_params, draft_adapter)
        else:
            if config.spec_decode:
                raise ValueError(
                    "EngineConfig.spec_decode requires "
                    "ragged_batching=True — verify rows are k-token "
                    "prefill-chunk rows of the unified ragged step")
            if getattr(adapter, "make_adapter_pool", None) is not None:
                raise ValueError(
                    "LoRA multiplexing requires ragged_batching — the "
                    "segmented adapter matmul rides the unified step")
            self._adapters = None
            self._ragged_step_lora_fn = None
            self._ragged_step_fn = None
            self._token_budget = 0
        # Adapter borrow per slot ("" = base model): released with the
        # slot on every terminal path.
        self._slot_adapter: Dict[int, str] = {}
        # Requests mid-incremental-prefill: [{req, slot, pos}].
        self._prefilling: List[Dict[str, Any]] = []
        # Requests whose admission prefill is being dispatched — a
        # crash mid-dispatch must fail them (they are in no other
        # registry yet).
        self._admitting: List[Request] = []

        if adapter.prefill_batch is not None:
            @partial(jax.jit, donate_argnums=(1,))
            def prefill_batched_fn(params, cache, tokens, true_lens,
                                   slot_or_pages, temps, seed, cur,
                                   slot_ids):
                logits, cache = adapter.prefill_batch(
                    params, tokens, true_lens, slot_or_pages, cache
                )
                toks = _sample(logits, temps, jax.random.key(seed[0]))
                # Padding rows' scatter ids are OOB — see prefill_batch_fn.
                return cache, toks, cur.at[slot_ids].set(toks, mode="drop")

            self._prefill_batched_fn = prefill_batched_fn
        else:
            self._prefill_batched_fn = None
        # One prefill program serves both modes: the adapter closure is
        # what interprets the third per-row arg (slot id vs page list).
        self._prefill_batch_fn = prefill_batch_fn
        self._decode_fn = decode_paged_fn if self._paged else decode_fn
        self._seed_counter = itertools.count(seed * 1_000_003 + 1)
        # Decode chunk ladder: descending powers of two (see
        # _chunk_size).
        ladder = []
        k = max(1, config.decode_chunk)
        while k >= 1:
            ladder.append(k)
            k //= 2
        self._chunk_ladder = tuple(ladder)
        # Per-slot control arrays riding dispatches as jit args,
        # rebuilt only when admission/finish dirties them.
        self._state_dirty = True
        self._active_arg = None
        self._temps_arg = None
        self._bt_arg = None
        self._lens_arg = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="llm-engine"
        )
        self._thread.start()
        self._fetcher = threading.Thread(
            target=self._fetch_loop, daemon=True, name="llm-fetch"
        )
        self._fetcher.start()

    def _init_spec(self, draft_params: Any,
                   draft_adapter: Optional[PagedEngineAdapter]) -> None:
        """Build the speculative-decoding plane: a second small paged
        pool for the draft model's KV (same allocator discipline, own
        OOB scratch page), the draft feed/chain programs, and the
        target verify program — the ragged step returning EXTRA logits
        at each verify row's candidate positions.  The BASE ragged
        program is untouched: batches without verify rows keep
        dispatching it, so spec-off output is the byte-identical oracle
        by construction."""
        config, adapter = self.config, self.adapter
        if adapter.ragged_step_verify is None:
            raise ValueError(
                "EngineConfig.spec_decode requires an adapter with "
                "ragged_step_verify (the unified step with extra "
                "verify logits)")
        da = draft_adapter if draft_params is not None else None
        if draft_params is None:
            # Self-draft: draft == target weights.  Every draft is
            # accepted, so this exercises/measures the verify path
            # (and drives the deterministic parity tests) rather than
            # saving device steps.
            draft_params = self._params
        da = da or adapter
        if da.ragged_step is None:
            raise ValueError(
                "spec_decode draft adapter must provide ragged_step")
        page = config.page_size
        R, Td = config.max_slots, self._token_budget
        self._draft_params = draft_params
        self._draft_pages = (config.spec_draft_pages
                             or config.max_slots * self._maxp)
        self._draft_cache = da.init_cache(self._draft_pages, page)
        self._draft_free = list(range(self._draft_pages))
        self._draft_slot_pages: Dict[int, List[int]] = {}
        self._draft_bt = np.full((R, self._maxp), self._draft_pages,
                                 np.int32)
        # Tokens of each slot's sequence already fed to the draft KV.
        self._draft_fed: Dict[int, int] = {}
        # A slot with a verify round in flight is fully idle (its
        # length mirror only advances at the accept boundary, host-side
        # at fetch); a slot whose device cur went stale after a verify
        # round re-seeds it through a host-token decode row.
        self._spec_inflight: set = set()
        self._spec_stale_cur: set = set()
        self._spec_ema = 1.0
        self._spec_cooldown = 0
        self._spec_rounds = 0
        self._spec_drafted_total = 0
        self._spec_accepted_total = 0
        self._spec_cooldowns = 0
        # Static width of the verify-logit gather: flat-buffer indices
        # of every verify row's k+1 candidate tokens, padded with 0.
        self._spec_tv = min(Td, R * (config.spec_k + 1))

        @partial(jax.jit, donate_argnums=(1,))
        def draft_feed_fn(params, cache, host_toks, tok_pos, row_slot,
                          row_start, row_len, row_off, bt):
            logits, cache = da.ragged_step(
                params, host_toks, tok_pos, row_slot, row_start,
                row_len, row_off, bt, cache)
            # Row logits sit at each row's LAST fed token: a row fed
            # through its sequence end yields draft token 1 directly.
            return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        @partial(jax.jit, donate_argnums=(1,))
        def draft_chain_fn(params, cache, prev, tok_pos, row_slot,
                           row_start, row_len, row_off, bt):
            # One-token rows at row_off = arange(R): the previous
            # step's [R] argmax IS the head of the flat token buffer.
            toks = jnp.zeros((Td,), jnp.int32).at[:R].set(prev)
            logits, cache = da.ragged_step(
                params, toks, tok_pos, row_slot, row_start, row_len,
                row_off, bt, cache)
            return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        self._draft_feed_fn = draft_feed_fn
        self._draft_chain_fn = draft_chain_fn

        @partial(jax.jit, donate_argnums=(1,))
        def ragged_step_spec_fn(params, cache, host_toks, decode_mask,
                                tok_slot, tok_pos, row_slot, row_start,
                                row_len, row_off, temps, seed, cur,
                                scatter_ids, bt, logit_idx):
            toks = jnp.where(decode_mask, cur[tok_slot], host_toks)
            logits, vlogits, cache = adapter.ragged_step_verify(
                params, toks, tok_pos, row_slot, row_start, row_len,
                row_off, bt, cache, logit_idx)
            sampled = _sample(logits, temps, jax.random.key(seed[0]))
            # Per-position target argmax of every verify candidate,
            # computed on device — the fetch carries k+1 ints per
            # verify row instead of k+1 logit vectors.
            ver = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
            # Verify rows keep OOB scatter ids: their row sample never
            # becomes the emitted token (the accept boundary decides).
            cur = cur.at[scatter_ids].set(sampled, mode="drop")
            return cache, (sampled, ver), cur

        self._ragged_step_spec_fn = ragged_step_spec_fn
        if (self._adapters is not None
                and adapter.ragged_step_lora_verify is not None):
            @partial(jax.jit, donate_argnums=(1,))
            def ragged_step_spec_lora_fn(params, cache, host_toks,
                                         decode_mask, tok_slot, tok_pos,
                                         row_slot, row_start, row_len,
                                         row_off, temps, seed, cur,
                                         scatter_ids, bt, pool,
                                         page_table, tok_adapter,
                                         logit_idx):
                toks = jnp.where(decode_mask, cur[tok_slot], host_toks)
                logits, vlogits, cache = \
                    adapter.ragged_step_lora_verify(
                        params, toks, tok_pos, row_slot, row_start,
                        row_len, row_off, bt, cache, pool, page_table,
                        tok_adapter, logit_idx)
                sampled = _sample(logits, temps,
                                  jax.random.key(seed[0]))
                ver = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
                cur = cur.at[scatter_ids].set(sampled, mode="drop")
                return cache, (sampled, ver), cur

            self._ragged_step_spec_lora_fn = ragged_step_spec_lora_fn
        elif self._adapters is not None:
            # A verify row can share a step with another slot's LoRA
            # row, so multiplexing + speculation needs the combined
            # program up front, not on first collision.
            raise ValueError(
                "spec_decode with LoRA multiplexing requires an "
                "adapter with ragged_step_lora_verify")
        else:
            self._ragged_step_spec_lora_fn = None
        self._spec_on = True

    # -- client API --------------------------------------------------------

    def submit(self, prompt: List[int], *, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0,
               request_id: Optional[str] = None,
               adapter_id: str = "") -> CompletionStream:
        if self._stopped.is_set():
            raise RuntimeError("engine is stopped (shut down or crashed)")
        if self._draining.is_set():
            # Uniform failover signal: the router resubmits elsewhere
            # exactly like a mid-stream preemption, with an empty
            # generated prefix.
            raise PreemptedError(
                "engine is draining: not admitting new requests",
                continuation={"prompt": list(prompt), "tokens": [],
                              "temperature": float(temperature),
                              "request_id": request_id or "",
                              "adapter_id": adapter_id})
        # Count the arrival before any admission decision: the signal
        # must see offered load, not just what survived shedding.
        self._arrived += 1
        self._tm["arrived"].inc()
        shed_after = self.config.shed_queue_age_s
        if shed_after is not None:
            age = self._admission_queue_age()
            if age > shed_after:
                # Admission control: a request queued now waits behind
                # work that is ALREADY over the SLO budget.  Record the
                # SHED terminal (no attempt ever runs, so this is the
                # request's whole story in this engine's ring) and fail
                # fast — goodput accounting is untouched: shed requests
                # produced zero tokens and protect the admitted ones.
                rid = (request_id or _reqev.get_request_id()
                       or f"{self._engine_id}-r{next(self._req_counter)}")
                self._ring.record(rid, _reqev.SHED,
                                  prompt_tokens=len(prompt),
                                  terminal_cause="ShedError",
                                  adapter_id=adapter_id)
                self._tm["shed"].inc()
                self._tm["terminal"].inc(tags={"state": _reqev.SHED})
                try:
                    from ray_tpu.util import flight_recorder
                    flight_recorder.trigger("shed", request_id=rid,
                                            queue_age_s=age)
                except Exception:
                    pass
                raise ShedError(queue_age_s=age)
        if adapter_id and self._adapters is None:
            raise ValueError(
                f"request carries adapter_id {adapter_id!r} but this "
                "engine has no adapter pool (model config without "
                "lora=, or non-ragged serving)")
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) >= self.config.max_seq_len:
            raise ValueError(
                f"prompt length {len(prompt)} >= max_seq_len "
                f"{self.config.max_seq_len}"
            )
        req = Request(
            prompt=list(prompt),
            max_new_tokens=max_new_tokens or self.config.max_new_tokens_default,
            temperature=float(temperature),
            stream=queue.Queue(),
            req_id=next(self._req_counter),
            trace_ctx=(tracing.capture_context()
                       if tracing.is_enabled() else None),
            adapter_id=adapter_id,
        )
        # Explicit id > the ambient one the serve replica installed
        # (router-minted, riding request metadata) > local mint.
        req.request_id = (request_id or _reqev.get_request_id()
                          or f"{self._engine_id}-r{req.req_id}")
        if self._paged:
            # Reject requests the page pool can NEVER satisfy — they
            # would otherwise wedge admission head-of-line forever.
            need = self._pages_needed(req)
            if need > self._num_pages:
                raise ValueError(
                    f"request needs {need} pages "
                    f"({len(prompt)}+{req.max_new_tokens} tokens, page "
                    f"{self.config.page_size}) but the pool has only "
                    f"{self._num_pages}"
                )
        self._ring.record(req.request_id, _reqev.QUEUED,
                          prompt_tokens=len(req.prompt),
                          adapter_id=req.adapter_id)
        log.debug("request %s queued (%d prompt tokens, max_new=%d)",
                  req.request_id, len(req.prompt), req.max_new_tokens)
        self._waiting.put(req)
        self._work.set()
        return CompletionStream(req, self)

    def cancel(self, request_id: str) -> None:
        """Cancel a request by id.  Idempotent; unknown or already
        terminal ids are a no-op.  Resolution happens on the engine
        loop (which owns slot/page state): the request reaches
        CANCELLED, its slot and pages are released, and its stream ends
        normally with the tokens generated so far."""
        if self._stopped.is_set():
            return
        with self._cancel_lock:
            self._cancels.add(request_id)
        self._work.set()

    def drain(self, grace_s: float = 5.0) -> int:
        """Preemption-aware drain: stop admitting, give requests
        already in a slot ``grace_s`` to finish, then evict the
        survivors with a PREEMPTED terminal whose PreemptedError
        carries the continuation payload (prompt + tokens generated so
        far + sampling state) — everything a surviving replica needs to
        resume with one re-prefill.  Requests that never reached a slot
        are evicted immediately (admission is the thing a drain stops).
        Blocking; callable from any thread; idempotent.  Returns the
        number of requests preempted so far."""
        if self._stopped.is_set():
            return self._preempted_count
        self._draining.set()
        self._work.set()
        deadline = time.monotonic() + max(0.0, grace_s)
        while (time.monotonic() < deadline
               and not self._stopped.is_set()
               and not self._drain_idle()):
            time.sleep(0.01)
        self._drain_evict.set()
        self._work.set()
        # The loop owns slot/page state; give it a bounded window to
        # run the eviction pass.
        evict_deadline = time.monotonic() + 5.0
        while (time.monotonic() < evict_deadline
               and not self._stopped.is_set()
               and not self._drain_idle()):
            time.sleep(0.01)
        return self._preempted_count

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def _drain_idle(self) -> bool:
        """No request the drain still has to account for."""
        if self._slot_req or not self._waiting.empty() or self._admitting:
            return False
        if self._prefilling or (self._paged and self._backlog):
            return False
        return True

    def generate(self, prompt: List[int], **kw) -> List[int]:
        return self.submit(prompt, **kw).result()

    @property
    def engine_id(self) -> str:
        """Stable name of this engine's request ring (the ``engine``
        key on state.list_requests rows)."""
        return self._engine_id

    def stats(self) -> Dict[str, Any]:
        out = {
            "engine": self._engine_id,
            "active_slots": self.config.max_slots - len(self._free_slots),
            "prefilling": len(getattr(self, "_prefilling", ())),
            "waiting": self._waiting.qsize(),
            "steps": self._steps,
            "tokens_out": self._tokens_out,
            "stall_events": self._stall_events,
            "requests": self._ring.counts_by_state(),
        }
        if self._paged:
            out["kv_pages_free"] = len(self._free_pages)
            out["kv_pages_cached"] = (self._prefix.cached_pages
                                      if self._prefix else 0)
        if self._prefix is not None:
            pstats = self._prefix.stats()
            pstats["hit_tokens"] = self._prefix_hit_tokens
            pstats["prompt_tokens"] = self._prefix_prompt_tokens
            out["prefix"] = pstats
            out["kv_migration"] = dict(self._mig_counts)
        if self._adapters is not None:
            out["adapters"] = self._adapters.stats()
        if self._spec_on:
            out["spec"] = {
                "rounds": self._spec_rounds,
                "drafted_tokens": self._spec_drafted_total,
                "accepted_tokens": self._spec_accepted_total,
                "accept_ratio": (
                    self._spec_accepted_total / self._spec_drafted_total
                    if self._spec_drafted_total else None),
                "cooldowns": self._spec_cooldowns,
                "ema": self._spec_ema,
                "k": self.config.spec_k,
                "draft_pages_free": len(self._draft_free),
            }
        return out

    def admission_queue_age(self) -> float:
        """Public face of the admission-queue-age gauge: seconds the
        oldest still-unadmitted request has waited (0.0 when nothing
        waits).  The leading overload signal — it climbs before any
        latency SLO blows — pushed to the controller for SLO-pressure
        autoscaling."""
        return self._admission_queue_age()

    def goodput_ratio(self) -> Optional[float]:
        """Cumulative goodput ratio (tokens from SLO-met requests over
        all terminal tokens — the raytpu_serve_goodput_ratio gauge),
        or None before any request reached a terminal state."""
        if not self._terminal_tokens:
            return None
        return self._good_tokens / self._terminal_tokens

    def arrivals_total(self) -> int:
        """Cumulative requests submitted (shed included) — the
        arrival process the predictive autoscaler takes a slope of."""
        return self._arrived

    def prefix_summary(self, max_entries: int = 256) -> Optional[dict]:
        """Compact routing summary of the prefix cache ({"page": …,
        "hashes": [chained CRC32 path hashes]}), or None when the
        cache is off.  Replicas push it to the controller, which
        re-broadcasts it on the route table so routers can prefer the
        replica holding the longest cached prefix."""
        if self._prefix is None:
            return None
        return self._prefix.summary(max_entries)

    def adapter_summary(self) -> Optional[dict]:
        """Compact routing summary of the adapter pool ({"adapters":
        [resident ids]}), or None when LoRA multiplexing is off.
        Published over the controller broadcast table exactly like
        prefix_summary, feeding the router's adapter-affinity arm."""
        if self._adapters is None:
            return None
        return self._adapters.summary()

    def doctor(self, deep: bool = True,
               timeout_s: float = 30.0) -> Dict[str, Any]:
        """Run one invariant audit pass (serve/audit) and return its
        report.  While the loop runs, the audit is enqueued for IT to
        execute between jitted dispatches (the loop owns every audited
        registry — same ownership rule as cancel and migration ops);
        once the engine is stopped the audit runs inline, because no
        mutator is left.  ``deep=False`` runs only the O(slots)
        conservation tier."""
        if self._stopped.is_set() or not self._thread.is_alive():
            # Let a stopping loop finish its final-audit/cleanup pass
            # first so the inline walk never races it.
            self._thread.join(timeout=5.0)
            return self._auditor.run(deep=deep)
        op: Dict[str, Any] = {"deep": bool(deep),
                              "done": threading.Event(),
                              "result": None, "error": None}
        with self._audit_lock:
            self._audit_ops.append(op)
        self._work.set()
        if not op["done"].wait(timeout_s):
            with self._audit_lock:
                try:
                    self._audit_ops.remove(op)
                except ValueError:
                    pass
            if not op["done"].is_set():
                if self._stopped.is_set():
                    self._thread.join(timeout=5.0)
                    return self._auditor.run(deep=deep)
                raise TimeoutError(
                    f"doctor audit not serviced within {timeout_s}s")
        if op["error"] is not None:
            raise op["error"]
        return op["result"]

    def doctor_report(self) -> Optional[Dict[str, Any]]:
        """The most recent audit report without running a new pass
        (None before the first audit)."""
        return self._auditor.last_report

    def shutdown(self):
        self._stopped.set()
        self._work.set()
        self._fetchq.put(None)  # release the fetcher

    # -- engine loop -------------------------------------------------------

    def _next_seed(self) -> np.ndarray:
        """Per-dispatch RNG seed as a tiny host array — the key derives
        INSIDE the jitted program (jax.random.split on the host is a
        ~75 ms dispatched program on tunneled devices)."""
        return np.asarray([next(self._seed_counter) & 0x7FFFFFFF],
                          np.uint32)

    def _bucket_for(self, n: int) -> int:
        for b in self.config.buckets():
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds max bucket")

    def _admit(self):
        if self._draining.is_set():
            return  # racing submits are preempted, never admitted
        if self._ragged:
            return self._admit_ragged()
        if self._paged:
            return self._admit_paged()
        while self._free_slots:
            # Pull as many waiting requests as there are free slots and
            # prefill them in one dispatch (padded to a {1,2,4,8} batch
            # and to the largest prompt bucket of the group).
            batch: List[Tuple[Request, int]] = []
            while self._free_slots and len(batch) < 8:
                try:
                    req = self._waiting.get_nowait()
                except queue.Empty:
                    break
                batch.append((req, self._free_slots.pop()))
            if not batch:
                return
            bucket = max(self._bucket_for(len(r.prompt))
                         for r, _ in batch)
            k = 1
            while k < len(batch):
                k *= 2
            tokens = np.zeros((k, bucket), np.int32)
            true_lens = np.zeros((k,), np.int32)
            slot_ids = np.zeros((k,), np.int32)
            temps = np.zeros((k,), np.float32)
            for i in range(k):
                req, slot = batch[min(i, len(batch) - 1)]  # pad = row copy
                tokens[i, : len(req.prompt)] = req.prompt
                true_lens[i] = len(req.prompt)
                slot_ids[i] = slot
                temps[i] = req.temperature
            self._admitting = [req for req, _slot in batch]
            toks_dev = self._run_prefill(k, tokens, true_lens, slot_ids,
                                         temps,
                                         self._scatter_ids(slot_ids,
                                                           len(batch)))
            self._finish_admit(batch, toks_dev, slot_ids)

    def _scatter_ids(self, slot_ids: np.ndarray, n_real: int) -> np.ndarray:
        """cur-scatter indices: real rows keep their slot, padding rows
        go OOB so their (differently-sampled) token is dropped."""
        out = np.array(slot_ids, np.int32)
        out[n_real:] = self.config.max_slots
        return out

    def _instrumented_dispatch(self, name, fn, args, span_name,
                               steps_attr=None, cost_steps=None):
        """Dispatch one jitted program; the FIRST dispatch of each
        named program also registers it in the device plane
        (util/xprof): lowered cost analysis must happen before the call
        (the program donates its cache — afterwards those buffers are
        deleted), while the timed call itself measures trace+compile
        wall.  Later dispatches pass straight through.  ``cost_steps``
        declares how many tokens the recorded cost covers (the
        per-token denominator for waterfall device estimates)."""
        if name in self._xprof_recorded:
            return fn(*args)
        self._xprof_recorded.add(name)
        lowered = None
        try:
            lowered = fn.lower(*args)
        except Exception:
            pass
        t0 = time.time()
        out = fn(*args)
        t1 = time.time()
        if lowered is not None:
            try:
                from ray_tpu.util import xprof

                xprof.record_compiled(
                    name, lowered, compile_time_s=t1 - t0,
                    span_name=span_name, steps_attr=steps_attr,
                    cost_steps=cost_steps, compiled_at=t1)
            except Exception:
                pass  # device-plane attribution is best-effort
        # The first dispatch's wall is XLA trace+compile, not a step:
        # tag its span compile=true so the roofline wall join skips it
        # and the victim request's waterfall excludes it (the xprof
        # compile window above carries the same exclusion when span
        # capture is off).
        if tracing.is_enabled():
            tracing.record_span(span_name, t0, t1,
                                attributes={"compile": True,
                                            "program": name})
        return out

    def _run_prefill(self, k, tokens, true_lens, slot_or_pages, temps,
                     slot_ids):
        """One admission dispatch: batched [K, S] forward when the
        adapter provides it, else the fori_loop-of-rows program.  The
        sampled first tokens scatter into the device cur INSIDE the
        program; host arrays ride the dispatch (no separate uploads).
        Callers set self._admitting first: a crash inside the dispatch
        must still fail these not-yet-registered requests."""
        # Padding rows are real device work, so they count as
        # dispatched prefill tokens (phase attribution, not goodput).
        self._tm["step_tokens"].inc(int(np.sum(true_lens)),
                                    tags={"phase": "prefill"})
        if self._prefill_batched_fn is not None:
            self._cache, toks_dev, self._cur_dev = \
                self._instrumented_dispatch(
                    "serve.prefill", self._prefill_batched_fn,
                    (self._params, self._cache, tokens, true_lens,
                     slot_or_pages, temps, self._next_seed(),
                     self._cur_dev, slot_ids),
                    span_name="llm.prefill",
                    cost_steps=float(np.sum(true_lens)),
                )
        else:
            self._cache, toks_dev, self._cur_dev = \
                self._instrumented_dispatch(
                    "serve.prefill", self._prefill_batch_fn,
                    (k, self._params, self._cache, tokens, true_lens,
                     slot_or_pages, temps, self._next_seed(),
                     self._cur_dev, slot_ids),
                    span_name="llm.prefill",
                    cost_steps=float(np.sum(true_lens)),
                )
        return toks_dev

    def _finish_admit(self, batch, toks_dev, slot_ids) -> None:
        """Post-prefill bookkeeping shared by both cache modes.  The
        first-token FETCH is deferred into the pipeline (one batched
        device_get covers several entries — each sync get costs a full
        ~100 ms round trip on tunneled devices); slots register NOW so
        decode chunks dispatch behind the prefill without waiting."""
        now = time.monotonic()
        for req, slot in batch:
            self._slot_req[slot] = req
            self._temps[slot] = req.temperature
            if req.admitted_at is None:
                req.admitted_at = now
            self._ring.record(
                req.request_id, _reqev.PREFILLING, slot=slot,
                num_pages=(len(self._slot_pages.get(slot, []))
                           if self._paged else None))
            # The pending first token counts against the budget until
            # the prefill entry is processed.
            self._inflight_tokens[slot] = \
                self._inflight_tokens.get(slot, 0) + 1
        # Cleared only AFTER every request is registered: a crash in
        # the window between the two registries would otherwise strand
        # clients (an overlap double-fail is a benign extra put).
        self._admitting = []
        self._state_dirty = True  # active/temps/bt/lens changed
        self._unprocessed += 1
        self._fetchq.put(("prefill", toks_dev, 0, list(batch),
                          time.monotonic()))

    def _alloc_slot_pages(self, req: Request,
                          need: Optional[int] = None) -> Optional[int]:
        """Claim a slot + its pages for a request; the block-table row
        gets real pages then the OOB sentinel (see _bt).  None when the
        pool can't cover it."""
        if need is None:
            need = self._pages_needed(req)
        if not self._free_slots:
            return None
        if len(self._free_pages) < need and self._prefix is not None:
            # Admission pressure evicts refcount-0 LRU cache pages
            # BEFORE the request queues: the cache borrows idle pool
            # capacity, it never competes with admission for it.
            freed = self._prefix.evict(need - len(self._free_pages))
            if freed:
                self._free_pages.extend(freed)
                self._tm["prefix_evicted"].inc(len(freed))
        if len(self._free_pages) < need:
            return None
        slot = self._free_slots.pop()
        pages = [self._free_pages.pop() for _ in range(need)]
        self._slot_pages[slot] = pages
        row = np.full((self._maxp,), self._num_pages, np.int32)
        row[: len(pages)] = pages
        self._bt[slot] = row
        self._update_page_gauges()
        return slot

    def _admit_slot_for(self, req: Request) -> Optional[Tuple[int, int]]:
        """Claim a slot + pages, borrowing the longest cached prefix
        when the prefix cache is on.  Returns (slot, start) — the
        ragged prefill resumes at ``start`` instead of 0 — or None
        under slot/page pressure (every borrowed ref released).

        Only FULL pages are cached and prefill resumes at the hit
        boundary, so shared pages are never written — except an exact
        full-prompt hit, where the mandatory last-token re-run (the
        sample needs its logits) lands inside the deepest shared page.
        That page is COW-split into a fresh page before scheduling."""
        if self._prefix is None:
            slot = self._alloc_slot_pages(req)
            return None if slot is None else (slot, 0)
        page = self.config.page_size
        hit_pages = self._prefix.acquire(req.prompt)
        d = len(hit_pages)
        start = hit = d * page
        cow = d > 0 and hit >= len(req.prompt)
        if cow:
            start = len(req.prompt) - 1
        need_total = self._pages_needed(req)
        slot = self._alloc_slot_pages(
            req, need=need_total - d + (1 if cow else 0))
        if slot is None:
            self._prefix.release(hit_pages)
            self._update_page_gauges()
            return None
        fresh = self._slot_pages[slot]
        if cow:
            src, dst = hit_pages[-1], fresh[0]
            self._cache = self._copy_page_fn(
                self._cache, np.int32(src), np.int32(dst))
            self._prefix.release([src])
            borrowed = hit_pages[:-1]
            pages = borrowed + [dst] + fresh[1:]
        else:
            borrowed = hit_pages
            pages = borrowed + fresh
        self._slot_pages[slot] = pages
        self._slot_borrowed[slot] = borrowed
        row = np.full((self._maxp,), self._num_pages, np.int32)
        row[: len(pages)] = pages
        self._bt[slot] = row
        req.prefix_hit = start
        self._prefix_hit_tokens += start
        self._prefix_prompt_tokens += len(req.prompt)
        self._tm["prefix_requests"].inc(
            tags={"outcome": "hit" if start else "miss"})
        self._tm["prefix_hit_depth"].observe(start)
        if self._prefix_prompt_tokens:
            self._tm["prefix_hit_ratio"].set(
                self._prefix_hit_tokens / self._prefix_prompt_tokens)
        self._update_page_gauges()
        return slot, start

    def _calibrate_collectives(self, probes: Dict[str, Callable]) -> None:
        """Time one decode-shaped collective per populated link class
        and observe raytpu_serve_collective_seconds with MEASURED wall
        time.  Runs once at engine construction: the first call
        compiles (untimed), the next three are timed — honest
        measurement rather than fabricated per-step attribution."""
        for link, probe in sorted(probes.items()):
            probe()  # compile
            for _ in range(3):
                t0 = time.perf_counter()
                probe()
                self._tm["collective_seconds"].observe(
                    time.perf_counter() - t0, tags={"link": link})

    def _count_collective_bytes(self, rows: int, steps: int = 1) -> None:
        """Per-dispatch analytic wire accounting for a decode of
        ``rows`` active slots × ``steps`` device steps."""
        if self._coll_bytes_fn is None or rows <= 0:
            return
        per_step = self._coll_bytes_fn(rows)
        for link, nbytes in per_step.items():
            if nbytes:
                self._tm["collective_bytes"].inc(
                    nbytes * steps, tags={"link": link})

    def _update_page_gauges(self) -> None:
        if not self._paged:
            return
        self._tm["kv_pages_free"].set(len(self._free_pages))
        cached = self._prefix.cached_pages if self._prefix else 0
        self._tm["kv_pages_cached"].set(cached)
        if self._prefix is not None:
            self._tm["prefix_cached_pages"].set(cached)

    def _pages_needed(self, req: Request) -> int:
        """Pages covering max(prefill bucket, prompt+max_new)."""
        page = self.config.page_size
        bucket = self._paged_bucket_for(len(req.prompt))
        return min(max(bucket // page,
                       -(-(len(req.prompt) + req.max_new_tokens) // page)),
                   self._maxp)

    def _paged_bucket_for(self, n: int) -> int:
        """Prefill bucket rounded UP to a page multiple: the paged
        prefill writes whole pages, so a bucket smaller than a page
        would write NO prompt k/v at all."""
        page = self.config.page_size
        b = self._bucket_for(n)
        return -(-b // page) * page

    def _admit_paged(self):
        """Admission with page allocation: a request needs pages for
        max(prefill bucket, prompt+max_new) tokens; when the pool can't
        cover it the request waits in the backlog (continuous batching
        under page pressure, the PagedAttention admission rule).  Long
        prompts (> prefill_chunk) go to the incremental-prefill track
        instead of a one-shot bucket."""
        page = self.config.page_size
        pc = self.config.prefill_chunk
        if pc and self._prefill_chunk_fn is not None:
            while self._free_slots:
                # Peek for a long-prompt request; admit it incrementally.
                if self._backlog and len(self._backlog[0].prompt) > pc:
                    req = self._backlog.pop(0)
                elif not self._backlog:
                    try:
                        req = self._waiting.get_nowait()
                    except queue.Empty:
                        break
                    if len(req.prompt) <= pc:
                        # Short prompt — normal batched admission path.
                        self._backlog.insert(0, req)
                        break
                else:
                    break
                slot = self._alloc_slot_pages(req)
                if slot is None:
                    self._backlog.insert(0, req)
                    break
                req.admitted_at = time.monotonic()
                self._ring.record(
                    req.request_id, _reqev.PREFILLING, slot=slot,
                    num_pages=len(self._slot_pages.get(slot, [])))
                self._prefilling.append({"req": req, "slot": slot,
                                         "pos": 0})
        while self._free_slots:
            batch: List[Tuple[Request, int]] = []
            group_bucket = None
            while self._free_slots and len(batch) < 8:
                if self._backlog:
                    req = self._backlog.pop(0)
                else:
                    try:
                        req = self._waiting.get_nowait()
                    except queue.Empty:
                        break
                bucket = self._paged_bucket_for(len(req.prompt))
                if group_bucket is None:
                    group_bucket = bucket
                elif bucket != group_bucket:
                    # One bucket per compiled prefill group; mismatches
                    # lead the next group.
                    self._backlog.append(req)
                    break
                need = self._pages_needed(req)
                if len(self._free_pages) < need:
                    self._backlog.append(req)  # wait for page frees
                    break
                slot = self._alloc_slot_pages(req, need=need)
                if slot is None:
                    self._backlog.append(req)
                    break
                batch.append((req, slot))
            if not batch:
                return
            bucket = group_bucket
            k = 1
            while k < len(batch):
                k *= 2
            tokens = np.zeros((k, bucket), np.int32)
            true_lens = np.zeros((k,), np.int32)
            pages_rows = np.zeros((k, bucket // page), np.int32)
            temps = np.zeros((k,), np.float32)
            for i in range(k):
                req, slot = batch[min(i, len(batch) - 1)]  # pad = copy
                tokens[i, : len(req.prompt)] = req.prompt
                true_lens[i] = len(req.prompt)
                pages_rows[i] = self._bt[slot][: bucket // page]
                temps[i] = req.temperature
            slot_ids = np.asarray(
                [batch[min(i, len(batch) - 1)][1] for i in range(k)],
                np.int32)
            for req, slot in batch:
                self._lens[slot] = len(req.prompt)
            self._admitting = [req for req, _slot in batch]
            toks_dev = self._run_prefill(k, tokens, true_lens, pages_rows,
                                         temps,
                                         self._scatter_ids(slot_ids,
                                                           len(batch)))
            self._finish_admit(batch, toks_dev, slot_ids)

    def _admit_ragged(self):
        """Ragged admission: EVERY request (short or long) claims its
        slot + pages up front and joins the incremental-prefill track;
        the unified step packs its prompt in budget-sized chunks
        beside live decode rows, so there is no separate one-shot
        prefill program to head-of-line-block behind."""
        from ray_tpu.serve.adapter_pool import AdapterPoolPressure

        while self._free_slots:
            if self._backlog:
                req = self._backlog.pop(0)
            else:
                try:
                    req = self._waiting.get_nowait()
                except queue.Empty:
                    return
            if req.adapter_id and self._adapters is not None:
                # Borrow the adapter's pages for the slot's lifetime.
                # Pressure (nothing evictable: every resident adapter
                # is borrowed) is transient — back off like page
                # pressure.  A loader error is terminal for the
                # request, never the engine.
                try:
                    self._adapters.acquire(req.adapter_id)
                except AdapterPoolPressure:
                    self._backlog.insert(0, req)
                    return
                except Exception as e:
                    req.finished_at = time.monotonic()
                    self._observe_request(
                        req, state=_reqev.FAILED,
                        cause=f"adapter load failed: {e!r}")
                    req.stream.put(RuntimeError(
                        f"adapter {req.adapter_id!r} load failed: {e!r}"))
                    continue
            got = self._admit_slot_for(req)
            if got is None:
                if req.adapter_id and self._adapters is not None:
                    self._adapters.release(req.adapter_id)
                self._backlog.insert(0, req)
                return
            slot, start = got
            if req.adapter_id:
                self._slot_adapter[slot] = req.adapter_id
            req.admitted_at = time.monotonic()
            self._ring.record(
                req.request_id, _reqev.PREFILLING, slot=slot,
                num_pages=len(self._slot_pages.get(slot, [])),
                prefix_hit=req.prefix_hit)
            self._prefilling.append({"req": req, "slot": slot,
                                     "pos": start})
            self._state_dirty = True  # bt rows changed

    def _draft_alloc(self, req: Request, slot: int) -> bool:
        """Lazily claim draft-pool pages for a slot's first
        speculative round (sized like the target allocation — the
        draft sequence tracks the target's).  False = draft pool
        exhausted; the slot simply plain-decodes until pages free."""
        if slot in self._draft_slot_pages:
            return True
        need = self._pages_needed(req)
        if len(self._draft_free) < need:
            return False
        pages = [self._draft_free.pop() for _ in range(need)]
        self._draft_slot_pages[slot] = pages
        row = np.full((self._maxp,), self._draft_pages, np.int32)
        row[: len(pages)] = pages
        self._draft_bt[slot] = row
        self._draft_fed[slot] = 0
        return True

    def _run_draft_feed(self, feed_rows: List[Dict[str, Any]],
                        feed_tokens: int):
        """Dispatch one draft catch-up/draft-1 feed over the ragged
        packer; returns the device [R] per-row argmax (row i fed
        through its sequence end = that slot's first draft token)."""
        from ray_tpu.ops.ragged_paged_attention import pack_ragged_batch

        R, Td = self.config.max_slots, self._token_budget
        (host_toks, _mask, _tok_slot, tok_pos, row_slot, row_start,
         row_len, row_off) = pack_ragged_batch(feed_rows, Td, R)
        self._draft_cache, nxt = self._instrumented_dispatch(
            "serve.spec_draft", self._draft_feed_fn,
            (self._draft_params, self._draft_cache, host_toks, tok_pos,
             row_slot, row_start, row_len, row_off,
             np.array(self._draft_bt)),
            span_name="llm.spec_draft")
        self._tm["step_tokens"].inc(feed_tokens,
                                    tags={"phase": "spec_draft"})
        return nxt

    def _spec_rem(self, req: Request) -> int:
        """Tokens the request may still emit (no in-flight charge —
        speculation only plans on fully-idle slots)."""
        return min(
            req.max_new_tokens - len(req.tokens),
            self.config.max_seq_len - len(req.prompt) - len(req.tokens),
        )

    def _spec_draft_round(self) -> Dict[int, List[int]]:
        """Plan and run ONE draft round: pick this dispatch's
        speculation candidates, catch the draft KV up to each
        candidate's sequence (one ragged feed whose row logits are the
        first drafts), chain up to spec_k - 1 single-token draft
        steps, and return {slot: draft tokens} for every candidate
        whose drafts are ready to verify.  The stacked draft samples
        come back through ONE device_get — the inherent sync point of
        drafting; the verify step itself stays pipelined."""
        R, Td = self.config.max_slots, self._token_budget
        k_cfg = self.config.spec_k
        active = sorted(self._slot_req)
        # Every active slot takes at least one token of the verify
        # dispatch's budget; a candidate spends k_eff on top of it.
        budget_left = Td - len(active)
        feed_left = Td
        # (slot, req, k_eff, seq_len) — candidate i is feed row i.
        plan: List[Tuple[int, Request, int, int]] = []
        feed_rows: List[Dict[str, Any]] = []
        catchup_rows: List[Dict[str, Any]] = []
        feed_tokens = 0
        for slot in active:
            req = self._slot_req[slot]
            if (slot in self._spec_inflight
                    or self._inflight_tokens.get(slot, 0)
                    or req.temperature != 0.0 or req.adapter_id
                    or req.first_token_at is None):
                continue
            k_eff = min(k_cfg, self._spec_rem(req) - 1, budget_left)
            if k_eff < 1 or not self._draft_alloc(req, slot):
                continue
            seq = req.prompt + req.tokens
            fed = self._draft_fed.get(slot, 0)
            backlog = seq[fed:]
            if (len(backlog) > feed_left
                    or len(feed_rows) + len(catchup_rows) >= R):
                # Can't catch up this round: feed what fits (the KV
                # sticks across rounds) and plain-decode meanwhile.
                # Catch-up rows pack AFTER every candidate row so
                # candidate i stays feed row i.
                if feed_left > 0 and len(feed_rows) + len(
                        catchup_rows) < R:
                    part = backlog[:feed_left]
                    catchup_rows.append(
                        {"slot": slot, "start": fed,
                         "tokens": [int(t) for t in part]})
                    self._draft_fed[slot] = fed + len(part)
                    feed_tokens += len(part)
                    feed_left = 0
                continue
            feed_rows.append({"slot": slot, "start": fed,
                              "tokens": [int(t) for t in backlog]})
            feed_left -= len(backlog)
            feed_tokens += len(backlog)
            self._draft_fed[slot] = len(seq)
            plan.append((slot, req, k_eff, len(seq)))
            budget_left -= k_eff
        feed_rows += catchup_rows
        if not feed_rows:
            return {}
        nxt = self._run_draft_feed(feed_rows, feed_tokens)
        if not plan:
            return {}
        max_k = max(k for _s, _r, k, _n in plan)
        outs = [nxt]
        chain_tokens = 0
        row_off = np.arange(R, dtype=np.int32)
        for m in range(2, max_k + 1):
            row_slot = np.zeros((R,), np.int32)
            row_start = np.zeros((R,), np.int32)
            row_len = np.zeros((R,), np.int32)
            tok_pos = np.zeros((Td,), np.int32)
            for i, (slot, _req, k_eff, seq_len) in enumerate(plan):
                if k_eff < m:
                    continue  # shorter chains idle as len-0 rows
                row_slot[i] = slot
                row_start[i] = tok_pos[i] = seq_len + m - 2
                row_len[i] = 1
                chain_tokens += 1
            self._draft_cache, nxt = self._instrumented_dispatch(
                "serve.spec_chain", self._draft_chain_fn,
                (self._draft_params, self._draft_cache, outs[-1],
                 tok_pos, row_slot, row_start, row_len, row_off,
                 np.array(self._draft_bt)),
                span_name="llm.spec_draft")
            outs.append(nxt)
        if chain_tokens:
            self._tm["step_tokens"].inc(chain_tokens,
                                        tags={"phase": "spec_draft"})
        stacked = np.asarray(jax.device_get(jnp.stack(outs)))
        return {slot: [int(stacked[m, i]) for m in range(k_eff)]
                for i, (slot, _req, k_eff, _n) in enumerate(plan)}

    def _dispatch_ragged_step(self) -> bool:
        """Pack and dispatch ONE unified ragged step: first a decode
        row (one token) or a speculative verify row (the slot's true
        last token + its k drafts) for every active slot with budget
        left, then prefill chunks from the incremental track until
        token_budget is full.  Decode rows are never displaced by
        prompt tokens — that priority IS the no-stall guarantee
        chunked prefill only approximates — and drafting never runs
        while prefill chunks contend for the budget.  Returns False
        when nothing fit (every slot budget-capped by in-flight
        tokens, no prompt tokens pending)."""
        from ray_tpu.ops.ragged_paged_attention import pack_ragged_batch

        T, R = self._token_budget, self.config.max_slots
        budget = T
        rows: List[Dict[str, Any]] = []
        parts: List[Tuple[str, Request, int, int]] = []
        scatter = np.full((R,), R, np.int32)  # OOB = sample dropped
        temps = np.zeros((R,), np.float32)
        n_decode = n_prefill = n_spec = 0
        # Draft a speculative round only on uncontended dispatches:
        # pending prefill chunks always win the budget over draft
        # tokens, and a cold acceptance EMA pauses drafting outright.
        drafts: Dict[int, List[int]] = {}
        spec_round = (self._spec_on and bool(self._slot_req)
                      and not self._prefilling)
        if spec_round and self._spec_cooldown > 0:
            self._spec_cooldown -= 1
            spec_round = False
        if spec_round:
            drafts = self._spec_draft_round()
        # Per-step adapter gather set: distinct adapter ids -> index
        # 1..K-1 (0 is the null adapter).  A row whose adapter would
        # overflow the set simply waits for the next step.
        step_adapters: Dict[str, int] = {}

        def _adapter_idx(req: Request) -> Optional[int]:
            if not req.adapter_id or self._adapters is None:
                return 0
            idx = step_adapters.get(req.adapter_id)
            if idx is None:
                if (len(step_adapters)
                        >= self.config.max_batch_adapters - 1):
                    return None  # gather set full this step
                idx = len(step_adapters) + 1
                step_adapters[req.adapter_id] = idx
            return idx

        for slot in sorted(self._slot_req):
            if budget <= 0 or len(rows) >= R:
                break
            if self._spec_on and slot in self._spec_inflight:
                continue  # verify round in flight: slot fully idle
            req = self._slot_req[slot]
            rem = min(
                req.max_new_tokens - len(req.tokens),
                self.config.max_seq_len - len(req.prompt)
                - len(req.tokens),
            ) - self._inflight_tokens.get(slot, 0)
            if rem <= 0:
                continue  # budget fully covered by in-flight steps
            ai = _adapter_idx(req)
            if ai is None:
                continue
            seq_last = int(req.tokens[-1] if req.tokens
                           else req.prompt[-1])
            dr = drafts.get(slot)
            if dr and budget >= len(dr) + 1 and rem > len(dr):
                # Verify row: the slot's true last token plus its k
                # drafts, packed as ONE k+1-token prefill-chunk row at
                # the current KV length.  Target logits at every
                # candidate position come back in the verify vector;
                # the row's own sample keeps the OOB scatter (the
                # accept boundary is resolved host-side at fetch).
                i = len(rows)
                rows.append({"slot": slot,
                             "start": int(self._lens[slot]),
                             "tokens": [seq_last] + dr, "adapter": ai})
                parts.append(("verify", req, slot,
                              {"drafts": dr, "row": i,
                               "base_len": int(self._lens[slot])}))
                budget -= len(dr) + 1
                n_spec += len(dr) + 1
                continue
            if (spec_round and dr is None
                    and self._inflight_tokens.get(slot, 0) > 0
                    and req.temperature == 0.0 and not req.adapter_id
                    and req.first_token_at is not None
                    and self._spec_rem(req) >= 2
                    and (slot in self._draft_slot_pages
                         or len(self._draft_free)
                         >= self._pages_needed(req))):
                # Spec-eligible slot with steps still in flight: hold
                # further decode rows so its pipeline drains and the
                # NEXT round can draft for it — k accepted tokens per
                # verify step beats depth-k pipelining of one-token
                # steps.  Cooldown (cold acceptance) and prefill
                # contention clear spec_round, restoring full-depth
                # plain pipelining.
                continue
            i = len(rows)
            if self._spec_on and slot in self._spec_stale_cur:
                # The device cur went stale at the last verify round
                # (the accept boundary was resolved host-side): a
                # host-token row computes the identical decode step
                # and its scatter re-seeds cur.
                rows.append({"slot": slot,
                             "start": int(self._lens[slot]),
                             "tokens": [seq_last], "adapter": ai})
            else:
                rows.append({"slot": slot,
                             "start": int(self._lens[slot]),
                             "tokens": None, "adapter": ai})
            parts.append(("decode", req, slot, i))
            scatter[i] = slot
            temps[i] = req.temperature
            budget -= 1
            n_decode += 1
        finishing = []
        for st in self._prefilling:
            if budget <= 0 or len(rows) >= R:
                break
            req, slot, pos = st["req"], st["slot"], st["pos"]
            chunk = req.prompt[pos:pos + budget]
            if not chunk:
                continue
            ai = _adapter_idx(req)
            if ai is None:
                continue
            is_last = pos + len(chunk) >= len(req.prompt)
            i = len(rows)
            rows.append({"slot": slot, "start": pos,
                         "tokens": [int(t) for t in chunk],
                         "adapter": ai})
            temps[i] = req.temperature
            if is_last:
                # The final chunk's sample is the request's first
                # token; mid-chunk rows keep the OOB scatter id.
                parts.append(("first", req, slot, i))
                scatter[i] = slot
                finishing.append(st)
            st["pos"] = pos + len(chunk)
            budget -= len(chunk)
            n_prefill += len(chunk)
        if not rows:
            return False
        self._refresh_state_args()
        if step_adapters:
            # LoRA variant: same program + the pool, the step's page
            # gather plan, and the per-token adapter index.  Batches
            # with no adapter rows never reach here — they stay on the
            # untouched base program below (zero overhead, bit-equal).
            (host_toks, decode_mask, tok_slot, tok_pos, row_slot,
             row_start, row_len, row_off, tok_adapter) = \
                pack_ragged_batch(rows, T, R, with_adapters=True)
        else:
            (host_toks, decode_mask, tok_slot, tok_pos, row_slot,
             row_start, row_len, row_off) = pack_ragged_batch(rows, T, R)
            tok_adapter = None
        if n_spec:
            # Flat-buffer positions of every verify row's k+1
            # candidate tokens (static [Tv], padded with index 0 —
            # harmless extra gathers) + each part's offset into the
            # returned verify vector.
            logit_idx = np.zeros((self._spec_tv,), np.int32)
            row_off_np = np.asarray(row_off)
            voff = 0
            for kind, _req, _slot, info in parts:
                if kind != "verify":
                    continue
                n = len(info["drafts"]) + 1
                off = int(row_off_np[info["row"]])
                logit_idx[voff:voff + n] = np.arange(off, off + n)
                info["voff"] = voff
                voff += n
        args = (self._params, self._cache, host_toks, decode_mask,
                tok_slot, tok_pos, row_slot, row_start, row_len,
                row_off, temps, self._next_seed(), self._cur_dev,
                scatter, self._bt_arg)
        if step_adapters:
            page_table = self._adapters.page_table(list(step_adapters))
            args += (self._adapters.device_pool, page_table, tok_adapter)
            name, fn = (("serve.ragged_spec",
                         self._ragged_step_spec_lora_fn)
                        if n_spec else
                        ("serve.ragged", self._ragged_step_lora_fn))
        else:
            name, fn = (("serve.ragged_spec", self._ragged_step_spec_fn)
                        if n_spec else
                        ("serve.ragged", self._ragged_step_fn))
        if n_spec:
            args += (logit_idx,)
        self._cache, toks_dev, self._cur_dev = \
            self._instrumented_dispatch(
                name, fn, args,
                span_name="llm.ragged", steps_attr="tokens",
                cost_steps=float(T),
            )
        now = time.monotonic()
        for kind, req, slot, i in parts:
            if kind == "verify":
                # The slot idles until its accept boundary returns:
                # lens only advances at fetch — that deferral IS the
                # rejection rollback point.
                self._inflight_tokens[slot] = len(i["drafts"]) + 1
                self._spec_inflight.add(slot)
                continue
            if kind == "decode":
                self._lens[slot] += 1  # mirror advances at dispatch
                if self._spec_on:
                    # A host-token decode row's scatter re-seeded cur.
                    self._spec_stale_cur.discard(slot)
            self._inflight_tokens[slot] = \
                self._inflight_tokens.get(slot, 0) + 1
        for st in finishing:
            self._prefilling.remove(st)
            req, slot = st["req"], st["slot"]
            self._lens[slot] = len(req.prompt)
            self._slot_req[slot] = req
            self._temps[slot] = req.temperature
            if req.admitted_at is None:
                req.admitted_at = now
        self._state_dirty = True
        self._steps += 1
        self._tm["step_tokens"].inc(n_decode, tags={"phase": "decode"})
        self._tm["step_tokens"].inc(n_prefill,
                                    tags={"phase": "prefill"})
        if n_spec:
            self._tm["step_tokens"].inc(n_spec,
                                        tags={"phase": "spec_verify"})
        self._count_collective_bytes(n_decode)
        if n_decode:
            self._tm["batch_size"].observe(n_decode)
        self._tm["queue_depth"].set(self._waiting.qsize()
                                    + len(self._backlog))
        self._tm["queue_age"].set(self._admission_queue_age())
        self._unprocessed += 1
        self._fetchq.put(("ragged", toks_dev, 1, list(parts),
                          time.monotonic()))
        return True

    def _emit(self, req: Request, slot: int, tok: int, burst: int = 1):
        """Record one generated token; finish/free the slot if done.
        ``burst`` > 1 = one of several tokens emitted by a single
        speculative verify step: the round's wall gap is split evenly
        across the burst so the ITL histogram stays an exact per-token
        partition of decode wall time."""
        self._slot_req.setdefault(slot, req)
        now = time.monotonic()
        if req.last_token_at is not None:
            gap = (now - req.last_token_at) / max(burst, 1)
            req.max_itl_s = max(req.max_itl_s, gap)
        req.last_token_at = now
        req.tokens.append(tok)
        req.stream.put(tok)
        self._tokens_out += 1
        self._ring.update(req.request_id,
                          generated_tokens=len(req.tokens))
        eos = self.config.eos_id is not None and tok == self.config.eos_id
        done = (
            eos
            or len(req.tokens) >= req.max_new_tokens
            or len(req.prompt) + len(req.tokens) >= self.config.max_seq_len
        )
        if done:
            cause = ("eos" if eos
                     else "max_new_tokens"
                     if len(req.tokens) >= req.max_new_tokens
                     else "max_seq_len")
            # KV is written for prompt + generated minus the last
            # sampled token (it was never fed back) — exactly the
            # prefix a future request can resume from.
            seq = req.prompt + req.tokens
            self._release_slot(slot, cache_tokens=seq[:len(seq) - 1])
            req.finished_at = now
            self._observe_request(req, state=_reqev.FINISHED, cause=cause)
            req.stream.put(_DONE)

    def _finish_verify(self, req: Request, slot: int,
                       info: Dict[str, Any], ver: np.ndarray,
                       now: float) -> None:
        """Resolve one fetched verify round: accept the longest draft
        prefix that matches the target argmaxes plus the free bonus
        token the target computed past it, rewind the slot's KV write
        offset (the host length mirror) to the accept boundary, and
        emit the burst.

        Rollback safety: the target wrote KV for all k+1 candidate
        positions in-place, but ``_lens[slot]`` only ever advances to
        ``base_len + 1 + j`` — every later step (and the draft feed)
        writes from the mirror, so rejected tail positions are
        overwritten before anything can attend to them, the grow-only
        int8 per-page scales merely stay conservative for the
        overwritten tail, and the finish path donates only
        ``seq[:-1]`` pages (always inside the accepted prefix) to the
        prefix trie — rejected positions never become cache-visible."""
        self._spec_inflight.discard(slot)
        # The whole k+1 charge pops at once: speculation only launches
        # on slots with zero in-flight tokens, so the charge is
        # exactly this round's.
        self._inflight_tokens.pop(slot, None)
        drafts, base_len = info["drafts"], info["base_len"]
        k = len(drafts)
        voff = info["voff"]
        row_ver = [int(t) for t in ver[voff:voff + k + 1]]
        j = 0
        while j < k and drafts[j] == row_ver[j]:
            j += 1
        self._spec_rounds += 1
        self._spec_drafted_total += k
        self._spec_accepted_total += j
        self._tm["spec_rounds"].inc()
        self._tm["spec_drafted"].inc(k)
        if j:
            self._tm["spec_accepted"].inc(j)
        self._tm["spec_accept_ratio"].set(
            self._spec_accepted_total / self._spec_drafted_total)
        self._spec_ema = 0.8 * self._spec_ema + 0.2 * (j / k)
        if (self._spec_cooldown == 0
                and self._spec_ema < self.config.spec_cold_accept):
            # Acceptance ran cold: plain-decode for a while, then
            # re-probe with a reset EMA.
            self._spec_cooldown = self.config.spec_cooldown_rounds
            self._spec_cooldowns += 1
            self._spec_ema = 1.0
        # Draft-KV rollback: the draft fed tokens seq[-1], d1..d(k-1)
        # at positions base_len+1 .. base_len+k, of which the first
        # min(j, k-1) drafts survive — d(k) was never fed back.
        self._draft_fed[slot] = base_len + 1 + min(j, k - 1)
        if req.finished_at is not None or self._slot_req.get(slot) is not req:
            return  # cancelled/preempted while the verify was in flight
        # Target-KV rollback happens HERE, before any emit can finish
        # the request and donate pages: the write offset rewinds to
        # the accept boundary.
        self._lens[slot] = base_len + 1 + j
        self._state_dirty = True
        # Device cur holds the verify row's (dropped) sample, not the
        # accept boundary — the next decode row for this slot feeds
        # the true last token from the host and re-seeds cur.
        self._spec_stale_cur.add(slot)
        req.spec_drafted += k
        req.spec_accepted += j
        self._ring.update(req.request_id,
                          spec_drafted=req.spec_drafted,
                          spec_accepted=req.spec_accepted)
        emitted = drafts[:j] + [row_ver[j]]
        for tok in emitted:
            self._emit(req, slot, int(tok), burst=len(emitted))
            if req.finished_at is not None:
                break  # EOS/limits inside the burst: drop the tail

    def _release_slot(self, slot: int, *,
                      cache_tokens: Optional[List[int]] = None) -> None:
        """Return a slot (and, paged, its pages) to the free pool —
        shared by the finish, cancel, and failure paths so terminal
        accounting can never leak capacity.

        With the prefix cache on: borrowed pages go back to the index
        (refcount -1, never the free list), and — on the FINISH path
        only (``cache_tokens`` = the KV-written token sequence) — the
        slot's full pages are offered to the trie; pages the trie
        adopts stay cached, the rest are freed.  Cancel/preempt/crash
        paths pass no cache_tokens: their tail pages may be partially
        written, so nothing is donated."""
        self._slot_req.pop(slot, None)
        aid = self._slot_adapter.pop(slot, "")
        if aid and self._adapters is not None:
            self._adapters.release(aid)
        self._free_slots.append(slot)
        self._state_dirty = True
        self._auditor.mark_dirty()
        if self._paged:
            if self._spec_on:
                self._spec_inflight.discard(slot)
                self._spec_stale_cur.discard(slot)
                self._draft_fed.pop(slot, None)
                dpages = self._draft_slot_pages.pop(slot, None)
                if dpages:
                    if _audit.corrupt(_audit.INJECT_DRAFT_PAGE):
                        dpages = dpages[1:]  # leak one draft page
                    self._draft_free.extend(dpages)
                    self._draft_bt[slot] = self._draft_pages
            pages = self._slot_pages.pop(slot, [])
            if self._prefix is not None:
                borrowed = self._slot_borrowed.pop(slot, [])
                release = borrowed
                if borrowed and _audit.corrupt(_audit.INJECT_TRIE_REF):
                    release = borrowed[1:]  # leak one trie borrow ref
                self._prefix.release(release)
                adopted: set = set()
                if cache_tokens is not None and not self._draining.is_set():
                    full = len(cache_tokens) // self.config.page_size
                    adopted = self._prefix.insert(cache_tokens,
                                                  pages[:full])
                owned = pages[len(borrowed):]
                self._free_pages.extend(p for p in owned
                                        if p not in adopted)
            else:
                self._free_pages.extend(pages)
            self._bt[slot] = self._num_pages
            self._lens[slot] = 0
            self._update_page_gauges()

    def _slo_met(self, req: Request) -> bool:
        """Did a FINISHED request meet every configured bound?  (No slo
        config = trivially met; callers gate on the terminal state.)"""
        slo = self.config.slo
        if slo is None:
            return True
        if slo.ttft_s is not None and (
                req.ttft_s is None or req.ttft_s > slo.ttft_s):
            return False
        if slo.tpot_s is not None:
            if req.first_token_at is None or len(req.tokens) < 2:
                return False
            tpot = ((req.finished_at - req.first_token_at)
                    / (len(req.tokens) - 1))
            if tpot > slo.tpot_s:
                return False
        if slo.e2e_s is not None and (
                req.finished_at - req.submitted_at) > slo.e2e_s:
            return False
        return True

    def _observe_request(self, req: Request, *,
                         state: str = _reqev.FINISHED,
                         cause: Optional[str] = None) -> None:
        """Terminal-state accounting for EVERY outcome — ring verdict,
        SLO/goodput/terminal counters for all three terminal states,
        latency histograms only for FINISHED (a cancelled request has
        no honest TTFT), and the request's span tree (queue wait →
        prefill → decode) when tracing is on.  Spans are recorded
        retroactively from the monotonic stamps the engine loop takes
        anyway, so the decode hot path itself carries no tracing
        code."""
        self._ring.record(req.request_id, state,
                          generated_tokens=len(req.tokens),
                          terminal_cause=cause,
                          spec_drafted=req.spec_drafted or None,
                          spec_accepted=(req.spec_accepted
                                         if req.spec_drafted else None))
        finished = state == _reqev.FINISHED
        met = finished and self._slo_met(req)
        if finished and not met and self.config.slo is not None:
            try:
                from ray_tpu.util import flight_recorder
                flight_recorder.trigger("slo_miss",
                                        request_id=req.request_id)
            except Exception:
                pass
        self._tm["terminal"].inc(tags={"state": state})
        self._tm["slo"].inc(tags={"outcome": "met" if met else "missed"})
        self._terminal_tokens += len(req.tokens)
        if met:
            self._good_tokens += len(req.tokens)
        if self._terminal_tokens:
            self._tm["goodput"].set(
                self._good_tokens / self._terminal_tokens)
        log.debug("request %s %s (cause=%s, %d tokens)",
                  req.request_id, state, cause, len(req.tokens))
        if finished:
            if req.ttft_s is not None:
                self._tm["ttft"].observe(req.ttft_s)
            if (req.first_token_at is not None and len(req.tokens) > 1):
                self._tm["tpot"].observe(
                    (req.finished_at - req.first_token_at)
                    / (len(req.tokens) - 1))
                self._tm["itl"].observe(req.max_itl_s)
        # Waterfall attribution: partition this request's e2e wall into
        # the raytpu_serve_request_overhead_seconds components and fold
        # it into the control-plane-share gauge (engine-local rows —
        # the router-inclusive join stays driver-side).
        try:
            from ray_tpu.serve import latency_attribution as _lat
            row = self._ring.row(req.request_id)
            if row is not None:
                _lat.observe_terminal(req.request_id, rows=[row])
        except Exception:
            pass  # attribution is best-effort accounting
        if not tracing.is_enabled():
            return
        # Monotonic stamps → wall clock for the trace view.
        off = time.time() - time.monotonic()
        root = tracing.record_span(
            "llm.request", req.submitted_at + off, req.finished_at + off,
            ctx=req.trace_ctx,
            attributes={"request_id": req.request_id,
                        "state": state,
                        "terminal_cause": cause,
                        "prompt_len": len(req.prompt),
                        "num_tokens": len(req.tokens)},
        )
        ctx = {"trace_id": root["trace_id"], "span_id": root["span_id"]}
        # A never-admitted terminal (cancelled/failed in queue) spends
        # its whole life in queue_wait.
        admitted = req.admitted_at or req.finished_at
        tracing.record_span("llm.queue_wait", req.submitted_at + off,
                            admitted + off, ctx=ctx)
        if req.first_token_at is not None:
            tracing.record_span("llm.prefill", admitted + off,
                                req.first_token_at + off, ctx=ctx)
            tracing.record_span("llm.decode", req.first_token_at + off,
                                req.finished_at + off, ctx=ctx,
                                attributes={"tokens": len(req.tokens)})

    def _chunk_size(self) -> int:
        """Largest compiled chunk that no active request can out-finish
        given tokens ALREADY IN FLIGHT (so only EOS, never the token
        budget, can end a request mid-chunk); 0 = every budget is fully
        covered by in-flight chunks — process those first.  The ladder
        is descending powers of two, so a gen-31 tail costs
        16+8+4+2+1 = 5 dispatches, not 16+4+4+4+1+1+1.

        Sizing keys off the LONGEST-remaining active request: shorter
        requests finish mid-chunk (their lanes decode garbage for the
        chunk's tail — batched decode computes every lane anyway, and
        overshoot writes are OOB-dropped via the block-table sentinel).
        min-sizing would fragment chunks whenever staggered arrivals
        mix progress levels — the open-loop serving pattern."""
        remaining = max(
            min(
                req.max_new_tokens - len(req.tokens),
                self.config.max_seq_len - len(req.prompt) - len(req.tokens),
            ) - self._inflight_tokens.get(slot, 0)
            for slot, req in self._slot_req.items()
        )
        for k in self._chunk_ladder:
            if k <= remaining:
                return k
        if remaining > 0:
            return self._chunk_ladder[-1]  # 1-step chunk covers any tail
        return 0

    def _dispatch_prefill_chunk(self) -> None:
        """Advance ONE incremental prefill by one chunk (interleaved
        with decode chunks, so a long prompt never blocks streams for
        its whole prefill — chunked prefill à la Sarathi/vLLM).  Each
        chunk enters the fetch pipe as a completion marker, so chunk
        dispatch is pipeline-gated like decode — the device queue never
        floods with back-to-back prefill chunks."""
        st = self._prefilling[0]
        req, slot, pos = st["req"], st["slot"], st["pos"]
        C = self.config.prefill_chunk
        chunk = req.prompt[pos:pos + C]
        t = np.zeros((1, C), np.int32)
        t[0, : len(chunk)] = chunk
        slot_arr = np.asarray([slot], np.int32)
        is_last = pos + len(chunk) >= len(req.prompt)
        scatter = (slot_arr if is_last
                   else np.asarray([self.config.max_slots], np.int32))
        # Attend only over pages covering the prompt so far (rounded to
        # a power of two for compile-shape bucketing) — a 256-token
        # chunk must not pay max_seq_len-wide attention.
        page = self.config.page_size
        covered = -(-(pos + len(chunk)) // page)
        nb = 1
        while nb < covered:
            nb *= 2
        nb = min(nb, self._maxp)
        self._cache, toks_dev, self._cur_dev = self._prefill_chunk_fn(
            self._params, self._cache, t,
            np.asarray([pos], np.int32),
            np.asarray([len(chunk)], np.int32),
            self._bt[slot][None, :nb],
            np.asarray([req.temperature], np.float32),
            self._next_seed(), self._cur_dev, scatter,
        )
        st["pos"] = pos + len(chunk)
        self._tm["step_tokens"].inc(len(chunk),
                                    tags={"phase": "prefill"})
        if is_last:
            self._prefilling.pop(0)
            self._lens[slot] = len(req.prompt)
            self._finish_admit([(req, slot)], toks_dev, slot_arr)
        else:
            # Completion marker: counts against the pipeline depth.
            self._unprocessed += 1
            self._fetchq.put(("pfchunk", toks_dev, 0, [],
                              time.monotonic()))

    def _refresh_state_args(self) -> None:
        """Rebuild the per-slot control arrays only when admission or a
        finish changed them; the arrays ride the next dispatch as jit
        arguments (no separate upload ops).  Between changes, lens
        feeds back device-side from the previous decode."""
        if not self._state_dirty:
            return
        active = np.zeros((self.config.max_slots,), bool)
        for slot in self._slot_req:
            active[slot] = True
        self._active_arg = active
        self._temps_arg = np.array(self._temps)
        if self._paged:
            self._bt_arg = np.array(self._bt)
            self._lens_arg = np.array(self._lens)
        self._state_dirty = False

    def _admission_queue_age(self) -> float:
        """Seconds since the oldest still-unadmitted request was
        submitted (0.0 when nothing waits).  Snapshot over the waiting
        queue's and backlog's internals — both only ever hold Request
        objects and a stale read just shifts the gauge one sample."""
        oldest = None
        for req in list(self._waiting.queue) + (
                list(self._backlog) if self._paged else []):
            if oldest is None or req.submitted_at < oldest:
                oldest = req.submitted_at
        return 0.0 if oldest is None else time.monotonic() - oldest

    def _note_step_time(self, wall_s: float, chunk: int) -> bool:
        """Record a decode chunk's dispatch-to-fetch wall time as
        per-step cost; returns True (and logs a warning) when the step
        blows past STALL_FACTOR x its running median.  The median is
        over the last 64 chunks, so a slow ramp moves the baseline
        while a one-off stall (page thrash, preempted host, device
        queue collapse) stands out."""
        per_step = wall_s / max(chunk, 1)
        history = sorted(self._step_walls)
        self._step_walls.append(per_step)
        if per_step > self._step_wall_hw:
            self._step_wall_hw = per_step
            self._tm["step_wall"].set(per_step)
        if len(history) < 8:
            return False
        median = history[len(history) // 2]
        if median > 0 and per_step > STALL_FACTOR * median:
            log.warning(
                "decode step stall: %.1f ms/step vs running median "
                "%.1f ms (x%.1f, chunk=%d, active=%d)",
                per_step * 1e3, median * 1e3, per_step / median,
                chunk, len(self._slot_req))
            self._stall_events += 1
            return True
        return False

    def _dispatch_decode(self, chunk: int) -> None:
        """Enqueue one decode chunk WITHOUT a host sync: cur and lens
        come back as device outputs of the previous chunk, so this runs
        while earlier chunks' tokens are still on the wire (the
        pipeline that hides the ~100 ms dispatch RTT of tunneled/remote
        devices)."""
        self._refresh_state_args()
        if self._paged:
            self._cache, toks_dev, self._cur_dev, self._lens_arg = \
                self._instrumented_dispatch(
                    "serve.decode", self._decode_fn,
                    (chunk, self._params, self._cache, self._cur_dev,
                     self._active_arg, self._temps_arg,
                     self._next_seed(), self._bt_arg, self._lens_arg),
                    span_name="llm.decode", steps_attr="tokens",
                    # One decode step produces one token per active
                    # request: a request's per-token device share is a
                    # full step, so the denominator is steps, not
                    # steps x slots.
                    cost_steps=float(chunk),
                )
            # Host mirror advances for slots active in THIS dispatch.
            for slot in self._slot_req:
                self._lens[slot] += chunk
        else:
            self._cache, toks_dev, self._cur_dev, _ = \
                self._instrumented_dispatch(
                    "serve.decode", self._decode_fn,
                    (chunk, self._params, self._cache, self._cur_dev,
                     self._active_arg, self._temps_arg,
                     self._next_seed()),
                    span_name="llm.decode", steps_attr="tokens",
                    cost_steps=float(chunk),
                )
        self._steps += chunk
        self._tm["step_tokens"].inc(chunk * len(self._slot_req),
                                    tags={"phase": "decode"})
        self._count_collective_bytes(len(self._slot_req), steps=chunk)
        self._tm["batch_size"].observe(len(self._slot_req))
        self._tm["queue_depth"].set(
            self._waiting.qsize()
            + (len(self._backlog) if self._paged else 0))
        self._tm["queue_age"].set(self._admission_queue_age())
        participants = list(self._slot_req.items())
        for slot, _req in participants:
            self._inflight_tokens[slot] = (
                self._inflight_tokens.get(slot, 0) + chunk
            )
        self._unprocessed += 1
        self._fetchq.put(("decode", toks_dev, chunk, participants,
                          time.monotonic()))

    def _fetch_loop(self) -> None:
        """Dedicated fetch thread: drain every queued entry, batch them
        into ONE device_get, hand the host arrays back to the engine
        loop in dispatch order.  Gets overlap dispatching AND each
        other's processing; the batch size self-balances to load."""
        while not self._stopped.is_set():
            entries = [self._fetchq.get()]
            if entries[0] is None:
                return
            while True:
                try:
                    nxt = self._fetchq.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    return
                entries.append(nxt)
            try:
                fetched = jax.device_get([e[1] for e in entries])
            except BaseException as e:
                self._fetched.put(e)
                return
            for entry, toks in zip(entries, fetched):
                # Speculative ragged steps return (sampled, verify)
                # as a tuple payload — keep the structure.
                if isinstance(toks, tuple):
                    toks = tuple(np.asarray(t) for t in toks)
                else:
                    toks = np.asarray(toks)
                self._fetched.put((entry, toks))

    def _process_fetched(self, block: bool) -> bool:
        """Emit every fetched entry available; returns True if any was
        processed.  ``block`` waits briefly for the next one (used when
        the loop has nothing to dispatch)."""
        processed = False
        while True:
            try:
                item = self._fetched.get(timeout=0.02) if block \
                    and not processed else self._fetched.get_nowait()
            except queue.Empty:
                return processed
            if isinstance(item, BaseException):
                raise item
            processed = True
            self._unprocessed -= 1
            (kind, _dev, chunk, participants, t_disp), toks = item
            now = time.monotonic()
            if kind == "decode":
                self._note_step_time(now - t_disp, chunk)
            if kind == "pfchunk":
                continue  # completion marker only (pipeline gating)
            if kind == "ragged":
                # One unified step: toks is the [R] row-sample vector;
                # participants carry (kind, req, slot, row) for decode
                # rows and final prefill chunks (mid-chunk rows have
                # nothing to emit).  Wall time feeds the same stall
                # watermark as decode — a ragged step IS a decode step
                # for every running stream in it.
                self._note_step_time(now - t_disp, 1)
                if isinstance(toks, tuple):
                    toks, ver = toks  # speculative step: (sampled, verify)
                else:
                    ver = None
                for rkind, req, slot, i in participants:
                    if rkind == "verify":
                        self._finish_verify(req, slot, i, ver, now)
                        continue
                    left = self._inflight_tokens.get(slot, 0) - 1
                    if left > 0:
                        self._inflight_tokens[slot] = left
                    else:
                        self._inflight_tokens.pop(slot, None)
                    if req.finished_at is not None:
                        continue  # cancelled/preempted while in flight
                    if rkind == "first":
                        req.first_token_at = now
                        self._ring.record(req.request_id,
                                          _reqev.DECODING)
                        self._emit(req, slot, int(toks[i]))
                    elif self._slot_req.get(slot) is req:
                        self._emit(req, slot, int(toks[i]))
                continue
            if kind == "prefill":
                for i, (req, slot) in enumerate(participants):
                    left = self._inflight_tokens.get(slot, 0) - 1
                    if left > 0:
                        self._inflight_tokens[slot] = left
                    else:
                        self._inflight_tokens.pop(slot, None)
                    if req.finished_at is not None:
                        # Cancelled while its prefill was in flight:
                        # the slot is already freed (and may even be
                        # re-owned) — emitting would re-register it.
                        continue
                    req.first_token_at = now
                    self._ring.record(req.request_id, _reqev.DECODING)
                    self._emit(req, slot, int(toks[i]))
                continue
            for slot, req in participants:
                left = self._inflight_tokens.get(slot, 0) - chunk
                if left > 0:
                    self._inflight_tokens[slot] = left
                else:
                    self._inflight_tokens.pop(slot, None)
                if self._slot_req.get(slot) is not req:
                    # Finished in an earlier chunk (EOS): overshoot.
                    continue
                for k in range(chunk):
                    self._emit(req, slot, int(toks[k, slot]))
                    if self._slot_req.get(slot) is not req:
                        break  # finished mid-chunk

    def _process_cancels(self) -> None:
        """Resolve pending cancellations against every registry the
        loop owns.  A cancelled request releases its slot/pages and
        reaches CANCELLED through the same `_observe_request` path as
        every other terminal — its stream ends with the normal _DONE
        marker.  Unknown ids (already terminal, or never this
        engine's) are dropped silently: cancel is idempotent."""
        with self._cancel_lock:
            if not self._cancels:
                return
            pending = set(self._cancels)
            self._cancels.clear()

        def _finish_cancel(req: Request, slot: Optional[int]) -> None:
            if slot is not None:
                self._release_slot(slot)
            req.finished_at = time.monotonic()
            self._observe_request(req, state=_reqev.CANCELLED,
                                  cause="cancelled")
            req.stream.put(_DONE)

        for slot, req in list(self._slot_req.items()):
            if req.request_id in pending:
                pending.discard(req.request_id)
                _finish_cancel(req, slot)
        if self._paged:
            for st in list(self._prefilling):
                if st["req"].request_id in pending:
                    pending.discard(st["req"].request_id)
                    self._prefilling.remove(st)
                    _finish_cancel(st["req"], st["slot"])
            for req in list(self._backlog):
                if req.request_id in pending:
                    pending.discard(req.request_id)
                    self._backlog.remove(req)
                    _finish_cancel(req, None)
        if pending:
            kept: List[Request] = []
            while True:
                try:
                    req = self._waiting.get_nowait()
                except queue.Empty:
                    break
                if req.request_id in pending:
                    pending.discard(req.request_id)
                    _finish_cancel(req, None)
                else:
                    kept.append(req)
            for req in kept:
                self._waiting.put(req)

    def _preempt_request(self, req: Request,
                         slot: Optional[int]) -> None:
        """Evict one request with a PREEMPTED terminal.  Its stream
        ends by raising PreemptedError carrying the continuation
        payload, so the consumer knows exactly which generated prefix
        it already holds."""
        if slot is not None:
            self._release_slot(slot)
        req.finished_at = time.monotonic()
        self._observe_request(req, state=_reqev.PREEMPTED,
                              cause="preempted")
        self._preempted_count += 1
        req.stream.put(PreemptedError(
            "replica draining: request evicted",
            continuation={"prompt": list(req.prompt),
                          "tokens": list(req.tokens),
                          "temperature": req.temperature,
                          "request_id": req.request_id,
                          "adapter_id": req.adapter_id}))

    def _process_drain(self) -> None:
        """Loop-side half of drain(): while draining, requests that
        never reached a slot are preempted immediately (admission has
        stopped, they can only rot); once the grace window expires
        (_drain_evict), everything still in a slot goes too."""
        if not self._draining.is_set():
            return
        while True:
            try:
                req = self._waiting.get_nowait()
            except queue.Empty:
                break
            self._preempt_request(req, None)
        if self._paged:
            for req in list(self._backlog):
                self._backlog.remove(req)
                self._preempt_request(req, None)
        if not self._drain_evict.is_set():
            return
        for st in list(self._prefilling):
            self._prefilling.remove(st)
            self._preempt_request(st["req"], st["slot"])
        for slot, req in list(self._slot_req.items()):
            self._preempt_request(req, slot)
        # Drain-evict leak fix (mirrors the clean-stop tail): open
        # migration leases belong to exports that can no longer
        # complete against a draining replica — release them, then
        # audit once so scale-down provably hands back a leak-free
        # pool.
        if not self._drain_audited:
            self._drain_audited = True
            self._release_open_leases()
            try:
                self._auditor.run(deep=True)
            except Exception:
                log.exception("drain-evict audit failed")

    # -- KV page migration (serve/kv_transfer) ------------------------------

    def _migration_op(self, kind: str, timeout_s: float, **kw) -> Any:
        """Enqueue one migration verb for the LOOP thread and wait for
        its result (the cache is donated between jitted dispatches, so
        only the loop may gather/scatter it — the same ownership rule
        the cancel queue follows).  Re-raises whatever the verb raised
        over there."""
        if not self._paged or self._prefix is None:
            raise RuntimeError(
                "KV migration requires the paged engine with "
                "EngineConfig.prefix_cache=True (transfers are keyed "
                "by the prefix trie's chained path hashes)")
        if self._stopped.is_set():
            raise RuntimeError("engine stopped")
        op: Dict[str, Any] = {"kind": kind, "done": threading.Event(),
                              "result": None, "error": None,
                              "abandoned": False, **kw}
        with self._mig_lock:
            self._mig_ops.append(op)
        self._work.set()
        if not op["done"].wait(timeout_s):
            with self._mig_lock:
                if not op["done"].is_set():
                    # Still queued: pull it so the loop never runs it.
                    # Already in flight: flag it abandoned — the loop
                    # auto-releases a lease nobody will ever own (a
                    # leaked lease pins its pages against eviction
                    # forever) and drops the unread result.
                    try:
                        self._mig_ops.remove(op)
                    except ValueError:
                        op["abandoned"] = True
                    raise TimeoutError(
                        f"migration op {kind!r} not serviced within "
                        f"{timeout_s}s")
            # done was set between the wait() expiry and taking the
            # lock: the op completed, its result is usable.
        if op["error"] is not None:
            raise op["error"]
        return op["result"]

    def migration_lease(self, tokens: Sequence[int], *,
                        timeout_s: float = 30.0) -> Optional[dict]:
        """Pin the longest cached full-page prefix of ``tokens`` under
        an eviction-proof migration lease.  Returns ``{"lease_id",
        "pages", "tokens"}`` (tokens truncated to the leased depth), or
        None when not even one full page is cached.  The caller owns
        the lease and MUST ``migration_release`` it on every path —
        success, failure, and cancel."""
        return self._migration_op("lease", timeout_s,
                                  tokens=[int(t) for t in tokens])

    def migration_export(self, lease_id: str, *, mode: str = "int8",
                         timeout_s: float = 30.0) -> dict:
        """Serialize a leased page run into one transfer dict (the
        kv_transfer.encode_pages wire format: payload + per-page int8
        scales + chained path hashes + analytic wire bytes)."""
        return self._migration_op("export", timeout_s,
                                  lease_id=lease_id, mode=mode)

    def migration_release(self, lease_id: str, *,
                          timeout_s: float = 30.0) -> bool:
        """Drop a migration lease.  Idempotent — unknown ids return
        False, because failure cleanup must never raise over a lease
        that already went away."""
        return self._migration_op("release", timeout_s,
                                  lease_id=lease_id)

    def migration_ingest(self, transfer: dict, *,
                         timeout_s: float = 30.0) -> int:
        """Ingest one transfer into the local pool + prefix trie:
        verify content identity (chained CRC32 over the tokens), skip
        depths already cached, scatter the payload into freshly
        allocated pages, and insert them into the trie.  Truncates to
        the free-page budget so the ingested prefix stays contiguous
        from the root.  Returns the number of pages ingested."""
        return self._migration_op("ingest", timeout_s, transfer=transfer)

    def export_hot_prefixes(self, *, max_pages: int = 256,
                            mode: str = "int8",
                            timeout_s: float = 60.0) -> List[dict]:
        """Prefix migration, source side: lease + export + release each
        hot cached path (recency order, deduped) — a cold or newly
        scaled replica ingests the returned transfers instead of
        recomputing its cache."""
        return self._migration_op("hot_prefixes", timeout_s,
                                  max_pages=max_pages, mode=mode)

    def _process_migrations(self) -> None:
        if self._prefix is None:
            return
        with self._mig_lock:
            if not self._mig_ops:
                return
            ops, self._mig_ops = self._mig_ops, []
        handlers = {"lease": self._mig_do_lease,
                    "export": self._mig_do_export,
                    "release": self._mig_do_release,
                    "ingest": self._mig_do_ingest,
                    "hot_prefixes": self._mig_do_hot_prefixes}
        for op in ops:
            try:
                op["result"] = handlers[op["kind"]](op)
            except Exception as e:  # re-raised at the waiter; loop lives
                op["error"] = e
            with self._mig_lock:
                # A waiter that timed out mid-service marked the op
                # abandoned: nobody will read the result, so a lease
                # acquired here would leak (eviction-pinned pages with
                # no owner to release them) — drop it on the spot.  The
                # lock orders this against the waiter's flag write: if
                # the waiter loses the race, it sees done set and uses
                # the result normally.
                if (op["abandoned"] and op["kind"] == "lease"
                        and op.get("result") is not None):
                    self._mig_do_release(
                        {"lease_id": op["result"]["lease_id"]})
                    op["result"] = None
                op["done"].set()

    @staticmethod
    def _mig_pad_ids(pages: Sequence[int], fill: int) -> np.ndarray:
        """Pad a page-id run to the next power of two (bounds the jit
        compile count) with ``fill`` — the OOB scratch page, a valid
        index whose contents nothing reads."""
        n = max(1, len(pages))
        padded = 1 << (n - 1).bit_length()
        return np.asarray(list(pages) + [fill] * (padded - len(pages)),
                          np.int32)

    def _mig_do_lease(self, op: dict) -> Optional[dict]:
        page = self.config.page_size
        pages = self._prefix.lease_acquire(op["tokens"])
        if not pages:
            return None
        lease_id = f"mig-{self._engine_id}-{next(self._mig_lease_ids)}"
        self._mig_leases[lease_id] = {
            "pages": list(pages),
            "tokens": op["tokens"][:len(pages) * page]}
        return {"lease_id": lease_id, "pages": list(pages),
                "tokens": list(self._mig_leases[lease_id]["tokens"])}

    def _mig_do_export(self, op: dict) -> dict:
        from ray_tpu.serve import kv_transfer as _kvt

        lease = self._mig_leases.get(op["lease_id"])
        if lease is None:
            raise KeyError(f"unknown migration lease {op['lease_id']!r}")
        t0 = time.monotonic()
        pages = lease["pages"]
        ids = self._mig_pad_ids(pages, self._num_pages)
        gathered = jax.device_get(self._mig_gather_fn(self._cache, ids))
        n = len(pages)
        gathered = {k: (v[:, :, :n] if k in ("k", "v") else v[:, :n])
                    for k, v in gathered.items()}
        transfer = _kvt.encode_pages(
            gathered, tokens=lease["tokens"],
            page_size=self.config.page_size, mode=op["mode"])
        self._mig_counts["pages_out"] += n
        self._mig_counts["bytes_out"] += transfer["wire_bytes"]
        self._tm["mig_pages"].inc(n, tags={"direction": "out"})
        self._tm["mig_bytes"].inc(transfer["wire_bytes"],
                                  tags={"direction": "out"})
        self._tm["mig_seconds"].observe(time.monotonic() - t0,
                                        tags={"op": "export"})
        return transfer

    def _mig_do_release(self, op: dict) -> bool:
        lease = self._mig_leases.pop(op["lease_id"], None)
        if lease is None:
            return False
        self._prefix.lease_release(lease["pages"])
        return True

    def _mig_do_ingest(self, op: dict) -> int:
        from ray_tpu.serve import kv_transfer as _kvt

        transfer = op["transfer"]
        page = self.config.page_size
        if int(transfer["page_size"]) != page:
            raise ValueError(
                f"transfer page_size {transfer['page_size']} != local "
                f"pool page_size {page}")
        _kvt.verify_transfer(transfer)
        t0 = time.monotonic()
        tokens = [int(t) for t in transfer["tokens"]]
        n_full = len(tokens) // page
        # Depths the trie already holds keep their local pages.  The
        # borrow stays held across the eviction AND the insert below:
        # evict() reclaims any refcount-0 page, so releasing the hit
        # pages first would let it free pages the insert is about to
        # re-adopt — the same page simultaneously on _free_pages and in
        # the trie, i.e. silent KV corruption.
        hit = self._prefix.acquire(tokens)
        try:
            have = len(hit)
            need = n_full - have
            if need <= 0:
                return 0
            if len(self._free_pages) < need:
                freed = self._prefix.evict(need - len(self._free_pages))
                self._free_pages.extend(freed)
                if freed:
                    self._tm["prefix_evicted"].inc(len(freed))
            # Truncate (never reorder): the ingested prefix must stay
            # contiguous from the root or the hashes stop meaning
            # "path".
            need = min(need, len(self._free_pages))
            if need <= 0:
                return 0
            dst = [self._free_pages.pop() for _ in range(need)]
            quantized = (isinstance(self._cache, dict)
                         and "k_scale" in self._cache)
            payload = _kvt.decode_payload(
                transfer, quantized, self._cache["k"].dtype,
                start_page=have, end_page=have + need)
            ids = self._mig_pad_ids(dst, self._num_pages)
            pad = len(ids) - need
            dev = {}
            for key in ("k", "v"):
                arr = payload[key]
                if pad:
                    arr = np.concatenate(
                        [arr, np.zeros((arr.shape[0], arr.shape[1], pad)
                                       + arr.shape[3:], arr.dtype)],
                        axis=2)
                dev[key] = arr
            if quantized:
                for key in ("k_scale", "v_scale"):
                    arr = payload[key]
                    if pad:
                        arr = np.concatenate(
                            [arr, np.zeros((arr.shape[0], pad)
                                           + arr.shape[2:], arr.dtype)],
                            axis=1)
                    dev[key] = arr
            self._cache = self._mig_scatter_fn(self._cache, ids, dev)
            adopted = self._prefix.insert(tokens[:(have + need) * page],
                                          hit + dst)
            for p in dst:
                if p not in adopted:  # lost a race with a local insert
                    self._free_pages.append(p)
            n_in = sum(1 for p in dst if p in adopted)
        finally:
            if hit:
                self._prefix.release(hit)
        wire = int(transfer.get("wire_bytes", 0))
        self._mig_counts["pages_in"] += n_in
        self._mig_counts["bytes_in"] += wire
        self._tm["mig_pages"].inc(n_in, tags={"direction": "in"})
        self._tm["mig_bytes"].inc(wire, tags={"direction": "in"})
        self._tm["mig_seconds"].observe(time.monotonic() - t0,
                                        tags={"op": "ingest"})
        self._update_page_gauges()
        return n_in

    # -- invariant audits (serve/audit, util/doctor) ------------------------

    def _process_audits(self) -> None:
        """Service queued doctor() ops on the loop thread — the only
        thread allowed to walk slot/page state while the engine
        runs."""
        with self._audit_lock:
            if not self._audit_ops:
                return
            ops, self._audit_ops = self._audit_ops, []
        for op in ops:
            try:
                op["result"] = self._auditor.run(deep=op["deep"])
            except Exception as e:
                op["error"] = e
            op["done"].set()

    def _release_open_leases(self) -> None:
        """Drop every open migration lease (shutdown/drain-evict leak
        fix): a lease still open here belongs to a client whose export
        can no longer complete, and an unreleased lease pins its pages
        against eviction forever — the final audit would rightly call
        that a leak."""
        if self._prefix is None or not self._mig_leases:
            return
        for lease_id in list(self._mig_leases):
            lease = self._mig_leases.pop(lease_id)
            try:
                self._prefix.lease_release(lease["pages"])
            except Exception:
                log.exception("migration lease %s did not release "
                              "cleanly during shutdown/drain", lease_id)

    def _mig_do_hot_prefixes(self, op: dict) -> List[dict]:
        out: List[dict] = []
        for path in self._prefix.hot_paths(op["max_pages"]):
            lease = self._mig_do_lease({"tokens": path["tokens"]})
            if lease is None:
                continue
            try:
                out.append(self._mig_do_export(
                    {"lease_id": lease["lease_id"], "mode": op["mode"]}))
            finally:
                self._mig_do_release({"lease_id": lease["lease_id"]})
        return out

    # Dispatched-but-unemitted entries: enough to keep the device and
    # the fetch pipe full; budget gating bounds per-slot run-ahead.
    _PIPELINE_DEPTH = 6

    def _loop(self):
        try:
            if self._mesh is not None:
                # Ambient mesh for the whole engine thread: program
                # traces (incl. the model's shard_map'd tp attention)
                # happen on first dispatch, in here.
                with self._mesh:
                    self._loop_body()
                return
            self._loop_body()
        except BaseException as e:  # engine crash — fail every client
            self._stopped.set()
            # The conftest deep-audit fixture skips crashed engines: a
            # loop that died mid-dispatch legitimately strands
            # allocator state, which is not a leak regression.
            self._crashed = True
            self._fetchq.put(None)  # release the fetcher thread too
            with self._mig_lock:  # release migration-op waiters too
                mig_ops, self._mig_ops = self._mig_ops, []
            for op in mig_ops:
                op["error"] = RuntimeError(
                    f"engine crashed before migration op "
                    f"{op['kind']!r} ran: {e!r}")
                op["done"].set()
            with self._audit_lock:  # release doctor() waiters too
                audit_ops, self._audit_ops = self._audit_ops, []
            for op in audit_ops:
                op["error"] = RuntimeError(
                    f"engine crashed before audit ran: {e!r}")
                op["done"].set()
            err = RuntimeError(f"LLM engine loop crashed: {e!r}")
            err.__cause__ = e
            failing = list(self._slot_req.values())
            failing += list(self._admitting)
            if self._paged:
                failing += list(self._backlog)
                failing += [st["req"] for st in self._prefilling]
            while True:
                try:
                    failing.append(self._waiting.get_nowait())
                except queue.Empty:
                    break
            seen = set()
            for req in failing:
                if id(req) in seen:
                    continue  # _admitting can overlap _slot_req
                seen.add(id(req))
                try:
                    # FAILED terminal accounting (ring + counters +
                    # spans) — best-effort: the crash itself must win.
                    if req.finished_at is None:
                        req.finished_at = time.monotonic()
                    self._observe_request(req, state=_reqev.FAILED,
                                          cause=repr(e))
                except Exception:
                    pass
                req.stream.put(err)
            raise

    def _loop_body(self):
        while not self._stopped.is_set():
            self._process_cancels()
            self._process_drain()
            self._process_migrations()
            self._process_audits()
            backlog = self._paged and (self._backlog or self._prefilling)
            if (not self._slot_req and self._waiting.empty()
                    and not backlog and self._unprocessed == 0):
                # Idle: settle the incremental audit debt, and
                # opportunistically run the rate-limited deep audit —
                # idle is the one time a full walk costs nobody
                # latency.
                self._auditor.maybe_incremental()
                if not self._draining.is_set():
                    self._auditor.maybe_idle_deep(time.monotonic())
                self._work.wait(timeout=0.05)
                self._work.clear()
                continue
            self._process_fetched(block=False)
            self._admit()
            self._auditor.maybe_incremental()
            dispatched = False
            if self._ragged:
                if ((self._slot_req or self._prefilling)
                        and self._unprocessed < self._PIPELINE_DEPTH):
                    dispatched = self._dispatch_ragged_step()
            else:
                if (self._prefilling
                        and self._unprocessed < self._PIPELINE_DEPTH):
                    # One incremental-prefill chunk per iteration rides
                    # the device queue BETWEEN decode chunks: running
                    # streams stall at most one chunk per long-prompt
                    # segment.
                    self._dispatch_prefill_chunk()
                    dispatched = True
                if (self._slot_req
                        and self._unprocessed < self._PIPELINE_DEPTH):
                    chunk = self._chunk_size()
                    if chunk > 0:
                        self._dispatch_decode(chunk)
                        dispatched = True
            if not dispatched and self._unprocessed > 0:
                # Nothing to dispatch — wait for the fetcher.
                self._process_fetched(block=True)
        # Clean stop: drain queued migration ops exactly like the crash
        # path does, so their waiters get an immediate "engine stopped"
        # instead of hanging until their timeout expires.
        with self._mig_lock:
            mig_ops, self._mig_ops = self._mig_ops, []
        for op in mig_ops:
            op["error"] = RuntimeError(
                f"engine stopped before migration op {op['kind']!r} ran")
            op["done"].set()
        # Shutdown leak fix: a clean stop releases every open
        # migration lease and every still-occupied slot (returning its
        # pages, adapter borrow, draft pages and borrowed prefix
        # pages) BEFORE the final deep audit, so clean shutdown is
        # provably leak-free — anything the audit still finds is a
        # real accounting bug, not an artifact of stopping mid-flight.
        self._release_open_leases()
        leftovers = set(self._slot_req)
        leftovers.update(st["slot"] for st in self._prefilling)
        self._prefilling.clear()
        for slot in sorted(leftovers):
            self._release_slot(slot)
        self._process_audits()  # queued doctor() ops still get served
        try:
            self._auditor.run(deep=True)
        except Exception:
            log.exception("final shutdown audit failed")
