"""Request router: power-of-two-choices replica scheduling.

Parity with the reference (ray: python/ray/serve/_private/router.py —
Router:944, PowerOfTwoChoicesReplicaScheduler:330).  The reference
probes two candidate replicas' queue lengths over RPC; here the router
tracks its own in-flight count per replica (decremented by a reaper
thread polling completion), which is the same signal the probe returns
in the single-router case, without the extra round-trip.
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import api
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.serve import request_events as _reqev
from ray_tpu.util import tracing

_TELEMETRY = None

# Weak registry of live routers so the doctor (serve/audit
# ``router_sync_checks``) can compare each router's replica table
# against the controller census without keeping routers alive.
_ROUTERS: "weakref.WeakSet" = weakref.WeakSet()


def live_routers() -> List["Router"]:
    """Every Router object still alive in this process, in a stable
    (app, deployment) order — the doctor's audit surface."""
    return sorted(_ROUTERS,
                  key=lambda r: (r.app_name, r.deployment_name))

# A request reaching this many attempts trips the flight recorder's
# retry_storm trigger (attempt numbers are 0-based; 3 = 4th try).
RETRY_STORM_ATTEMPTS = 3


def _telemetry():
    """Router metric singletons (re-registered on refetch — see
    llm_engine._telemetry for the registry-clear rationale)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "requests": metrics.Counter(
                "raytpu_serve_router_requests_total",
                "Requests routed to a replica, by deployment.",
                tag_keys=("deployment",),
            ),
            "inflight": metrics.Gauge(
                "raytpu_serve_router_inflight",
                "Requests assigned but not yet completed, by deployment.",
                tag_keys=("deployment",),
            ),
            "retries": metrics.Counter(
                "raytpu_serve_request_retries_total",
                "In-flight request attempts re-enqueued on a surviving "
                "replica after a death or preemption, by deployment.",
                tag_keys=("deployment",),
            ),
            "prefix_routed": metrics.Counter(
                "raytpu_serve_router_prefix_routed_total",
                "Assignments where cache-aware routing picked the "
                "replica claiming the longest cached prefix of the "
                "prompt (vs falling back to least-loaded), by "
                "deployment.",
                tag_keys=("deployment",),
            ),
            "adapter_routed": metrics.Counter(
                "raytpu_serve_router_adapter_routed_total",
                "Assignments where adapter-affinity routing picked a "
                "replica already holding the request's LoRA adapter "
                "resident, by deployment.",
                tag_keys=("deployment",),
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


class _ReplicaInfo:
    def __init__(self, replica_id: str, handle, max_ongoing: int,
                 is_async: bool = False, prefix_summary=None,
                 role: str = "unified", adapter_summary=None,
                 reported_ongoing: float = 0.0, draining: bool = False):
        self.replica_id = replica_id
        self.handle = handle
        self.max_ongoing = max_ongoing
        self.is_async = is_async
        self.inflight = 0
        # Prefix-cache routing summary the replica last published
        # through the controller broadcast ({"page", "hashes"}), or
        # None.  A routing HINT only — the engine re-matches exactly.
        self.prefix_summary = prefix_summary
        # Disaggregated serving role ("prefill"|"decode"|"unified"):
        # fresh LLM streams prefer prefill replicas; migrated streams
        # resume on their handoff target (prefer_replica).
        self.role = role
        # Resident-adapter summary ({"adapters": [ids…]}) for LoRA
        # multiplexing.  Also a hint: the engine pool reloads on miss.
        self.adapter_summary = adapter_summary
        # Ongoing-request count the replica last pushed through the
        # controller (broadcast row 7) — the cross-router load signal.
        self.reported_ongoing = reported_ongoing
        # Broadcast row 8: the controller marked this replica DRAINING
        # (policy scale-down or preemption notice).  Still routable —
        # retries and migrated streams may land here — but fresh
        # requests prefer non-draining peers so the drain settles.
        self.draining = draining

    def live_load(self) -> float:
        """Load signal for every routing arm: the larger of this
        router's own in-flight count (which sees its assignments a push
        interval before the controller does) and the replica's
        controller-reported ongoing count (which sees OTHER routers'
        assignments this router never will)."""
        return max(float(self.inflight), self.reported_ongoing)


def _load_bounded(candidates: List["_ReplicaInfo"],
                  slack: float = 2.0) -> List["_ReplicaInfo"]:
    """Candidates within ``slack`` requests of the lightest one's live
    load — the single imbalance bound both affinity arms (adapter
    residency and prefix cache) select within.  Affinity outside the
    bound is a hotspot, not a win: a replica more than ``slack``
    requests above the floor serves a cache hit slower than a warm-miss
    on an idle peer, so the overflow falls through to the p2c arm."""
    floor = min(r.live_load() for r in candidates)
    return [r for r in candidates if r.live_load() <= floor + slack]


def _payload_tokens(args: tuple) -> Optional[List[int]]:
    """Prompt tokens of an LLM data-plane payload ({"tokens": [...]})
    — what cache-aware routing matches against replica summaries.
    None for non-LLM deployments (any other payload shape)."""
    if args and isinstance(args[0], dict):
        toks = args[0].get("tokens")
        if isinstance(toks, (list, tuple)) and toks:
            return list(toks)
    return None


class Router:
    """One per DeploymentHandle; subscribes to the controller's routing
    table via long-poll and assigns requests to replicas."""

    def __init__(self, app_name: str, deployment_name: str):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._replicas: Dict[str, _ReplicaInfo] = {}
        self._outstanding: Dict[ObjectRef, str] = {}
        # Multiplexing affinity: model_id → replica_id of the replica
        # that last served it (parity: the reference's model-aware
        # replica scheduler preferring replicas with the model resident).
        self._model_affinity: Dict[str, str] = {}
        self._stopped = threading.Event()
        self._client = None
        self._tm = _telemetry()
        # Router-side request ring: the failover view (QUEUED →
        # RETRYING per failed attempt → terminal) of every request this
        # router owns, federated into state.list_requests next to the
        # engine-side rings.  The router holds the strong ref.
        self._ring = _reqev.RequestEventBuffer(
            f"router:{app_name}/{deployment_name}")
        _reqev.register(self._ring)
        _ROUTERS.add(self)
        self._subscribe()
        threading.Thread(
            target=self._reaper_loop, daemon=True,
            name=f"router-reaper-{deployment_name}",
        ).start()

    # -- routing table -----------------------------------------------------

    def _subscribe(self):
        from ray_tpu.serve.controller import replica_set_key
        from ray_tpu.serve.long_poll import LongPollClient

        key = replica_set_key(self.app_name, self.deployment_name)

        def subscribe():
            # Re-resolve CONTROLLER_NAME on every (re)connect rather
            # than pinning one handle: a replacement controller is a
            # NEW actor.  Going through _get_or_create_controller means
            # the first data-plane client to notice an outage also
            # RESURRECTS the control plane from its checkpoint — the
            # router keeps serving its last-known table meanwhile.
            from ray_tpu.serve import _get_or_create_controller

            controller = _get_or_create_controller()

            def listen(seen: Dict[str, int]):
                return api.get(controller.long_poll.remote(seen))

            return listen

        self._client = LongPollClient(
            subscribe(), {key: self._update_replicas},
            resubscribe=subscribe)

    def _update_replicas(self, table: List[Tuple[str, Any, int]]) -> None:
        """table: [(replica_id, actor_handle, max_ongoing_requests,
        is_async, prefix_summary, role, adapter_summary,
        reported_ongoing, draining)]"""
        with self._cv:
            fresh: Dict[str, _ReplicaInfo] = {}
            for row in table:
                replica_id, handle, max_ongoing = row[:3]
                is_async = bool(row[3]) if len(row) > 3 else False
                summary = row[4] if len(row) > 4 else None
                role = row[5] if len(row) > 5 else "unified"
                adapters = row[6] if len(row) > 6 else None
                ongoing = float(row[7]) if len(row) > 7 else 0.0
                draining = bool(row[8]) if len(row) > 8 else False
                old = self._replicas.get(replica_id)
                if old is not None:
                    old.max_ongoing = max_ongoing
                    old.is_async = is_async
                    old.prefix_summary = summary
                    old.role = role
                    old.adapter_summary = adapters
                    old.reported_ongoing = ongoing
                    old.draining = draining
                    fresh[replica_id] = old
                else:
                    fresh[replica_id] = _ReplicaInfo(
                        replica_id, handle, max_ongoing, is_async,
                        summary, role, adapters, ongoing, draining
                    )
            removed = [rid for rid in self._replicas if rid not in fresh]
            self._replicas = fresh
            # Drop affinity entries pointing at replicas that left the
            # routing table (they'd pin models to ghosts forever).
            self._model_affinity = {
                m: rid for m, rid in self._model_affinity.items()
                if rid in fresh
            }
            # The broadcast table is AUTHORITATIVE, not a merge input:
            # replica ids are unique forever, so an id absent from the
            # new table is retired or dead and never comes back.
            # Release its outstanding entries now — critical on a
            # controller-recovery rebroadcast, where a replica that
            # died DURING the outage would otherwise keep its ghost
            # in-flight charges (and, via them, the inflight gauge)
            # until the reaper happened to poll one of its refs.
            if removed:
                gone = set(removed)
                orphaned = [ref for ref, rid in self._outstanding.items()
                            if rid in gone]
                for ref in orphaned:
                    del self._outstanding[ref]
                self._tm["inflight"].set(
                    len(self._outstanding),
                    tags={"deployment": self.deployment_name})
            self._cv.notify_all()

    def audit_view(self) -> Dict[str, Any]:
        """Point-in-time view of this router's replica table for the
        doctor's router↔controller sync check."""
        with self._lock:
            return {
                "app": self.app_name,
                "deployment": self.deployment_name,
                "replica_ids": sorted(self._replicas),
            }

    # -- assignment --------------------------------------------------------

    def assign(self, method_name: str, args: tuple, kwargs: dict,
               timeout: Optional[float] = None,
               exclude: Optional[set] = None,
               model_id: str = "",
               request_id: Optional[str] = None) -> Tuple[ObjectRef, str]:
        """Pick a replica (power of two choices on in-flight counts,
        respecting max_ongoing_requests backpressure) and submit.
        ``exclude``: replica ids observed dead by the caller — never
        re-picked (ids are unique forever, so this can't starve a healthy
        replica; if everything is excluded we wait for the controller's
        replacement broadcast).  ``request_id``: pass the same id on a
        retry so every attempt shares one identity end to end."""
        deadline = None if timeout is None else time.monotonic() + timeout
        # Mint the end-to-end request id HERE (or inherit one from an
        # upstream hop): it rides request metadata to the replica,
        # which installs it as ambient context for the user callable —
        # LLMEngine.submit, spans, and log lines all pick it up.
        request_id = (request_id or _reqev.get_request_id()
                      or _reqev.new_request_id())
        # The request's root span: replica selection (with its queue
        # wait) and the submit happen inside it, so the replica's task
        # span — and everything the user code spawns — parent here.
        with tracing.span(
                "serve.request",
                attributes={"deployment": self.deployment_name,
                            "method": method_name,
                            "request_id": request_id}):
            with tracing.span("serve.queue_wait"):
                chosen = self._select_replica(deadline, timeout, exclude,
                                              model_id,
                                              tokens=_payload_tokens(args))
            metadata = {"request_id": request_id}
            if model_id:
                metadata["multiplexed_model_id"] = model_id
            entry = (chosen.handle.handle_request_async if chosen.is_async
                     else chosen.handle.handle_request)
            ref = entry.remote(method_name, args, kwargs, metadata)
        self._tm["requests"].inc(
            tags={"deployment": self.deployment_name})
        with self._cv:
            self._outstanding[ref] = chosen.replica_id
            self._tm["inflight"].set(
                len(self._outstanding),
                tags={"deployment": self.deployment_name})
        return ref, chosen.replica_id

    def assign_streaming(self, method_name: str, args: tuple, kwargs: dict,
                         timeout: Optional[float] = None,
                         exclude: Optional[set] = None,
                         model_id: str = "",
                         request_id: Optional[str] = None,
                         prefer_replica: Optional[str] = None):
        """Streaming assignment: dispatch handle_request_streaming on
        the chosen replica and return (ObjectRefGenerator, replica_id,
        request_id).  Streaming in-flight accounting is caller-driven —
        call finish_streaming(replica_id, ...) when the stream ends,
        since the reaper has no single completion ref to poll.
        ``prefer_replica``: route here if it is a live candidate (a
        migrated stream resumes on the replica its KV pages landed on);
        falls back to normal selection when it is gone."""
        deadline = None if timeout is None else time.monotonic() + timeout
        request_id = (request_id or _reqev.get_request_id()
                      or _reqev.new_request_id())
        # A migrated stream is past its prefill: whether or not its
        # preferred target is still alive, it must not be steered back
        # into the prefill pool by the role filter.
        resumed = (prefer_replica is not None
                   or bool(args and isinstance(args[0], dict)
                           and args[0].get("_disagg_resumed")))
        with tracing.span(
                "serve.request",
                attributes={"deployment": self.deployment_name,
                            "method": method_name,
                            "streaming": True,
                            "request_id": request_id}):
            with tracing.span("serve.queue_wait"):
                chosen = self._select_replica(deadline, timeout, exclude,
                                              model_id,
                                              tokens=_payload_tokens(args),
                                              prefer_replica=prefer_replica,
                                              resumed=resumed)
            metadata = {"request_id": request_id}
            if model_id:
                metadata["multiplexed_model_id"] = model_id
            gen = chosen.handle.handle_request_streaming.remote(
                method_name, args, kwargs, metadata
            )
        self._tm["requests"].inc(
            tags={"deployment": self.deployment_name})
        return gen, chosen.replica_id, request_id

    def finish_streaming(self, replica_id: str, *,
                         died: bool = False) -> None:
        """End-of-stream bookkeeping for assign_streaming: release the
        in-flight slot; ``died`` evicts the replica (and every
        outstanding entry attributed to it) without waiting for the
        controller's next broadcast."""
        with self._cv:
            info = self._replicas.get(replica_id)
            if info is not None and info.inflight > 0:
                info.inflight -= 1
            if died:
                self._evict_replica_locked(replica_id)
            self._cv.notify_all()

    # -- failover ring ------------------------------------------------------

    def note_queued(self, request_id: str, prompt_tokens: int = 0,
                    adapter_id: str = "") -> None:
        self._ring.record(request_id, _reqev.QUEUED,
                          prompt_tokens=prompt_tokens,
                          adapter_id=adapter_id)

    def note_retry(self, request_id: str, attempt: int, replica_id: str,
                   reason: str) -> None:
        """One failed attempt: RETRYING transition + attempt history +
        the retries counter."""
        self._ring.record(request_id, _reqev.RETRYING, attempt=attempt,
                          attempt_info={"attempt": attempt,
                                        "replica": replica_id,
                                        "reason": reason})
        self._tm["retries"].inc(
            tags={"deployment": self.deployment_name})
        if attempt >= RETRY_STORM_ATTEMPTS:
            # One request bouncing across this many replicas is a
            # storm, not a blip — arm the flight recorder.
            try:
                from ray_tpu.util import flight_recorder
                flight_recorder.trigger(
                    "retry_storm", request_id=request_id,
                    attempt=attempt, deployment=self.deployment_name)
            except Exception:
                pass

    def note_migrating(self, request_id: str, attempt: int,
                       replica_id: str, target: str) -> None:
        """One planned prefill→decode handoff (serve/kv_transfer):
        MIGRATING transition + attempt history.  Not a retry — the
        attempt SUCCEEDED and its pages moved — so the retries counter
        stays untouched."""
        self._ring.record(request_id, _reqev.MIGRATING, attempt=attempt,
                          attempt_info={"attempt": attempt,
                                        "replica": replica_id,
                                        "reason": f"migrated:{target}"})

    def note_terminal(self, request_id: str, state: str,
                      cause: Optional[str] = None,
                      generated_tokens: Optional[int] = None) -> None:
        self._ring.record(request_id, state,
                          generated_tokens=generated_tokens,
                          terminal_cause=cause)

    def _select_replica(self, deadline, timeout, exclude, model_id,
                        tokens=None, prefer_replica=None,
                        resumed=False):
        from ray_tpu.serve.prefix_index import match_depth

        with self._cv:
            while True:
                candidates = [
                    r for r in self._replicas.values()
                    if r.inflight < r.max_ongoing
                    and (not exclude or r.replica_id not in exclude)
                ]
                if candidates:
                    chosen = None
                    if prefer_replica is not None:
                        # Migrated stream: its KV pages live on exactly
                        # one replica — go there if it is still a live
                        # candidate (else normal selection; the replay
                        # fallback recomputes, never stalls).
                        chosen = next(
                            (r for r in candidates
                             if r.replica_id == prefer_replica), None)
                    if chosen is None:
                        # Draining replicas (policy scale-down,
                        # preemption notice) stay candidates of last
                        # resort: fresh requests prefer non-draining
                        # peers so the drain settles, but when every
                        # peer is saturated or gone a draining replica
                        # beats a queue-wait (it bounces with
                        # PreemptedError and the retry lands right).
                        live = [r for r in candidates if not r.draining]
                        if live:
                            candidates = live
                    if (chosen is None and tokens is not None
                            and not resumed):
                        # Disaggregated deployment: fresh LLM payloads
                        # prefer a prefill-role replica.  Soft filter —
                        # when no prefill replica is a candidate (all
                        # dead/saturated), any replica serves the
                        # request unified rather than blocking.  Resumed
                        # (migrated) streams skip it: if their handoff
                        # target died, cache-aware selection over every
                        # candidate should run — steering them back to a
                        # prefill replica would skew the role split.
                        prefill = [r for r in candidates
                                   if r.role == "prefill"]
                        if prefill:
                            candidates = prefill
                    if chosen is None and model_id:
                        # Sticky multiplexed routing: prefer the replica
                        # that already holds this model, if it has slack.
                        sticky = self._model_affinity.get(model_id)
                        chosen = next((r for r in candidates
                                       if r.replica_id == sticky), None)
                        if chosen is not None:
                            # Refresh recency so bounded eviction drops
                            # cold models, not hot ones.
                            self._model_affinity.pop(model_id, None)
                    if chosen is None and model_id:
                        # Adapter-resident arm: a replica whose pushed
                        # summary already lists this adapter skips the
                        # load/upload miss path entirely.  Selection
                        # runs inside the shared _load_bounded set, so
                        # one hot adapter can't turn affinity into a
                        # hotspot (the p2c arm below spreads the
                        # overflow).
                        resident = [
                            r for r in _load_bounded(candidates)
                            if model_id in (r.adapter_summary or {})
                            .get("adapters", ())
                        ]
                        if resident:
                            chosen = min(resident,
                                         key=_ReplicaInfo.live_load)
                            self._tm["adapter_routed"].inc(
                                tags={"deployment": self.deployment_name})
                    if chosen is None and tokens is not None:
                        # Cache-aware arm: prefer the replica claiming
                        # the longest cached prefix of this prompt
                        # (hit depth in tokens; ties break on load).
                        # Scans the whole _load_bounded set, not a p2c
                        # sample — the summary match is local and
                        # cheap, and a sampled pair would miss the
                        # holder half the time at 4+ replicas.  The
                        # bound is the same one the adapter arm uses:
                        # a deep cached prefix on an overloaded replica
                        # is slower end-to-end than a recompute on an
                        # idle one.
                        best_depth = 0
                        for r in _load_bounded(candidates):
                            depth = match_depth(tokens, r.prefix_summary)
                            if depth > best_depth or (
                                    depth == best_depth and depth > 0
                                    and r.live_load()
                                    < chosen.live_load()):
                                chosen, best_depth = r, depth
                        if chosen is not None:
                            self._tm["prefix_routed"].inc(
                                tags={"deployment": self.deployment_name})
                    if chosen is None:
                        if len(candidates) > 2:
                            candidates = random.sample(candidates, 2)
                        chosen = min(candidates, key=_ReplicaInfo.live_load)
                    if model_id:
                        self._model_affinity[model_id] = chosen.replica_id
                        if len(self._model_affinity) > 4096:
                            # Bounded map under model churn: drop the
                            # oldest entry (insertion order ≈ LRU here).
                            self._model_affinity.pop(
                                next(iter(self._model_affinity))
                            )
                    chosen.inflight += 1
                    break
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"no replica of {self.deployment_name!r} became "
                        f"available within {timeout}s"
                    )
                self._cv.wait(0.05 if remaining is None else min(remaining, 0.05))
        return chosen

    def _evict_replica_locked(self, replica_id: Optional[str]) -> None:
        """Drop a dead replica from the local table AND release every
        outstanding entry still attributed to it.  A dead actor seals
        ActorDiedError on all of its queued refs at once; popping only
        the ref that happened to complete first would leave the rest
        charged to a replica that no longer exists — the inflight gauge
        (and any future broadcast re-adding the same id) would leak.
        Caller holds self._cv."""
        if replica_id is None:
            return
        self._replicas.pop(replica_id, None)
        # Purge sticky multiplexing affinity pointing at the dead
        # replica NOW — the next request for those adapters must
        # re-resolve on a survivor, not wait for the controller's
        # rebroadcast to prune ghosts.
        self._model_affinity = {
            m: rid for m, rid in self._model_affinity.items()
            if rid != replica_id
        }
        orphaned = [ref for ref, rid in self._outstanding.items()
                    if rid == replica_id]
        for ref in orphaned:
            del self._outstanding[ref]
        self._tm["inflight"].set(
            len(self._outstanding),
            tags={"deployment": self.deployment_name})

    def _reaper_loop(self):
        """Decrement in-flight counts as results land (parity: the
        completion callbacks the reference attaches to assignments).
        A result carrying ActorDiedError evicts the replica from the
        local table immediately — faster than waiting for the
        controller's next broadcast — and releases every outstanding
        entry attributed to the dead replica in the same pass."""
        from ray_tpu.core.exceptions import ActorDiedError

        rt = api.runtime()
        while not self._stopped.wait(0.002):
            with self._cv:
                refs = list(self._outstanding)
            if not refs:
                continue
            done = [r for r in refs if rt.store.contains(r.id)]
            if not done:
                continue
            with self._cv:
                for ref in done:
                    replica_id = self._outstanding.pop(ref, None)
                    if replica_id is None:
                        continue  # released by an earlier eviction
                    info = self._replicas.get(replica_id)
                    if info is not None and info.inflight > 0:
                        info.inflight -= 1
                    err = rt.store.peek_error(ref.id)
                    if isinstance(err, ActorDiedError):
                        self._evict_replica_locked(replica_id)
                self._tm["inflight"].set(
                    len(self._outstanding),
                    tags={"deployment": self.deployment_name})
                self._cv.notify_all()

    def num_outstanding(self) -> int:
        with self._lock:
            return len(self._outstanding)

    def stop(self):
        self._stopped.set()
        if self._client is not None:
            self._client.stop()
