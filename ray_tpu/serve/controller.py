"""Serve controller: deployment reconciliation, health, autoscaling.

Parity with the reference (ray: python/ray/serve/controller.py —
ServeController:80; serve/_private/deployment_state.py —
DeploymentState:1155, DeploymentStateManager:2258; application
lifecycle serve/_private/application_state.py; autoscaling
serve/_private/autoscaling_policy.py).  A single named actor owns all
target state and runs a reconcile loop: start/stop/replace replica
actors until the running set matches the target, health-check them,
and broadcast routing tables over long-poll.
"""

from __future__ import annotations

import logging
import math
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import api
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.deployment import DeploymentInfo
from ray_tpu.serve.long_poll import LongPollHost
from ray_tpu.serve.replica import ReplicaActor

log = logging.getLogger(__name__)

CONTROLLER_NAME = "serve::controller"
ROUTES_KEY = "routes"

RECONCILE_PERIOD_S = 0.05

# Controller-checkpoint blob: layout version INSIDE the GCS snapshot
# envelope (which carries its own format version + monotonic seq), and
# the cluster-KV slot it persists through.  The KV lives on the driver
# runtime, so it survives the controller ACTOR's death — and inherits
# disk durability when gcs_persist_path is configured.
CKPT_VERSION = 1
CKPT_NAMESPACE = "serve"
CKPT_KEY = b"controller::checkpoint"

_TELEMETRY = None


def _telemetry():
    """Controller metric singletons (re-registered on refetch — see
    llm_engine._telemetry for the registry-clear rationale)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "drains": metrics.Counter(
                "raytpu_serve_replica_drains_total",
                "Replica drains begun (preemption notices, SIGTERM, "
                "drain_replica RPCs), by deployment.",
                tag_keys=("deployment",),
            ),
            "reconcile_errors": metrics.Counter(
                "raytpu_serve_reconcile_errors_total",
                "Exceptions swallowed by the controller reconcile "
                "loop — nonzero means the control plane is limping.",
            ),
            "shard_members": metrics.Gauge(
                "raytpu_serve_shard_group_members",
                "Member processes of a multi-host shard-group replica "
                "(rank 0 + shard members; 0 once the group is torn "
                "down), by deployment and replica.",
                tag_keys=("deployment", "replica"),
            ),
            "autoscale_decisions": metrics.Counter(
                "raytpu_serve_autoscale_decisions_total",
                "Applied autoscaling decisions, by deployment, "
                "direction (up = capacity added; down = retirement "
                "through the DRAINING path) and reason (ongoing / "
                "queue_age / goodput / arrival_slope — the last is the "
                "predictive path: scaled on arrival-rate slope before "
                "any queue formed).",
                tag_keys=("deployment", "direction", "reason"),
            ),
            "autoscale_target": metrics.Gauge(
                "raytpu_serve_autoscale_target_groups",
                "Shard groups (replicas) the reconciler is currently "
                "driving the deployment toward.",
                tag_keys=("deployment",),
            ),
            "autoscale_actual": metrics.Gauge(
                "raytpu_serve_autoscale_actual_groups",
                "Shard groups (replicas) currently RUNNING, by "
                "deployment — lags the target while replicas start "
                "or drain.",
                tag_keys=("deployment",),
            ),
            "restarts": metrics.Counter(
                "raytpu_serve_controller_restarts_total",
                "Controller recoveries: a replacement controller "
                "adopted a previous epoch's state from the persisted "
                "checkpoint after the controller actor died.",
            ),
            "ckpt_seq": metrics.Gauge(
                "raytpu_serve_controller_checkpoint_seq",
                "Monotonic save counter of the controller checkpoint "
                "(resumed across controller generations, so it never "
                "regresses).",
            ),
            "ckpt_age": metrics.Gauge(
                "raytpu_serve_controller_checkpoint_age_seconds",
                "Seconds since the controller checkpoint was last "
                "persisted — climbing under traffic means the "
                "checkpointer is wedged and a crash would lose state.",
            ),
            "orphans_adopted": metrics.Counter(
                "raytpu_serve_orphans_adopted_total",
                "Checkpointed replicas found alive at controller "
                "recovery and adopted back into the census.",
            ),
            "orphans_killed": metrics.Counter(
                "raytpu_serve_orphans_killed_total",
                "Live replica actors from a previous controller epoch "
                "with no checkpoint record, hard-killed at recovery "
                "(they are invisible to reconciliation and would leak "
                "forever).",
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


def replica_set_key(app_name: str, deployment_name: str) -> str:
    return f"replicas::{app_name}::{deployment_name}"


class _Replica:
    def __init__(self, replica_id: str, handle, creation_ref):
        self.replica_id = replica_id
        self.handle = handle
        self.creation_ref = creation_ref
        # STARTING | RUNNING | DRAINING | STOPPING.  DRAINING = alive
        # and still routable (it finishes what it has, rejects new
        # work) while a replacement starts; it leaves the broadcast
        # table only once RUNNING capacity is back at target.
        self.state = "STARTING"
        self.health_ref = None
        self.last_health_check = time.monotonic()
        # Drain bookkeeping: retirement waits for in-flight work to
        # settle (ongoing_ref polls the replica) up to drain_deadline.
        self.drain_deadline = None
        self.ongoing_ref = None
        # Latest prefix-cache routing summary the replica pushed
        # ({"page": …, "hashes": […]}), re-broadcast on the route
        # table so routers can prefer the replica holding the longest
        # cached prefix.  None = no cache / nothing cached yet.
        self.prefix_summary = None
        # Latest resident-adapter routing summary the replica pushed
        # ({"adapters": [ids…]}), re-broadcast the same way so routers
        # can prefer the replica already holding a request's LoRA
        # adapter.  None = multiplexing off / nothing resident yet.
        self.adapter_summary = None
        # Multi-host shard group (config.shard_group): rank 0 IS this
        # replica's handle (the streaming endpoint the router
        # addresses); members holds the rank >= 1 ShardMemberActor
        # handles whose death fails the whole group.
        self.members: List[Tuple[int, Any]] = []
        self.pg = None
        self.mesh_shape = ""
        self.member_ping_refs = None
        # Disaggregated serving role (config.disagg): "prefill" |
        # "decode" | "unified".  Assigned at start by live-role census
        # so a killed prefill replica's replacement is prefill again.
        self.role = "unified"
        # Ongoing-request count carried on the last broadcast row for
        # this replica — metric pushes rebroadcast only when the live
        # count moved a whole request away from it (live-load routing
        # without a 20 Hz broadcast storm).
        self.bcast_ongoing = 0.0


class _DeploymentState:
    """Target + running state for one deployment (parity:
    serve/_private/deployment_state.py DeploymentState)."""

    def __init__(self, app_name: str, info: DeploymentInfo):
        self.app_name = app_name
        self.info = info
        self.target_replicas = info.config.initial_target_replicas()
        self.replicas: Dict[str, _Replica] = {}
        self.next_replica_idx = 0
        self.deleting = False
        # autoscaling bookkeeping: id -> (ts, ongoing, queue_age, goodput)
        self.metrics: Dict[str, Tuple[float, float, float,
                                      Optional[float]]] = {}
        # Arrival-rate signal (predictive scale-up): per-replica
        # cumulative arrival counts fold reset-tolerantly into one
        # deployment-wide total that feeds an EWMA rate + slope
        # (serve/signals.ArrivalSignal).  Lazy: only built when the
        # config enables upscale_slope_threshold, so the reactive-only
        # path stays byte-for-byte what it was.
        self._arrival_prev: Dict[str, float] = {}
        self._arrival_total = 0.0
        self._arrival_signal = None
        self._scale_intent: Optional[Tuple[int, float]] = None
        # Last APPLIED scale decision ({direction, from, to, reason,
        # ts}) — surfaced on list_replicas rows for `raytpu list
        # replicas`.  None until the policy first moves the target.
        self.last_decision: Optional[Dict[str, Any]] = None
        # What the last routing-table broadcast actually announced:
        # [(replica_id, draining)] — the doctor's census_broadcast
        # check recomputes the expected table from the replica census
        # and diffs it against this.
        self.last_broadcast: List[Tuple[str, bool]] = []

    @property
    def config(self) -> DeploymentConfig:
        return self.info.config

    def apply_new_info(self, info: DeploymentInfo) -> None:
        """Code or config update: lightweight path for user_config-only
        changes, full rolling replace otherwise."""
        old = self.info
        self.info = info
        auto = info.config.autoscaling_config
        if auto is not None:
            # Preserve the autoscaled target across idempotent redeploys —
            # only clamp into the (possibly new) bounds.
            self.target_replicas = max(
                auto.min_replicas, min(auto.max_replicas, self.target_replicas)
            )
        else:
            self.target_replicas = info.config.initial_target_replicas()
        same_code = (
            old.func_or_class is info.func_or_class
            and old.init_args == info.init_args
            and old.init_kwargs == info.init_kwargs
        )
        if same_code and old.config.user_config != info.config.user_config:
            for r in self.replicas.values():
                if r.state == "RUNNING":
                    r.handle.reconfigure.remote(info.config.user_config)
        elif not same_code:
            # Replace everything; reconcile restarts at the new version.
            for r in self.replicas.values():
                r.state = "STOPPING"

    # -- autoscaling -------------------------------------------------------

    def _signal(self):
        cfg = self.config.autoscaling_config
        if cfg is None or cfg.upscale_slope_threshold is None:
            return None
        if self._arrival_signal is None:
            from ray_tpu.serve.signals import ArrivalSignal

            self._arrival_signal = ArrivalSignal(
                half_life_s=cfg.arrival_half_life_s,
                window_s=cfg.arrival_slope_window_s)
        return self._arrival_signal

    def record_metric(self, replica_id: str, ongoing: float, ts: float,
                      queue_age: float = 0.0,
                      goodput: Optional[float] = None,
                      arrivals: Optional[float] = None):
        self.metrics[replica_id] = (ts, ongoing, queue_age, goodput)
        if arrivals is None:
            return
        # Fold the replica's cumulative arrival count into the
        # deployment total: first push baselines (a fresh replica's
        # history is unknown), a count that went backwards means the
        # replica restarted (the new count IS the delta).
        prev = self._arrival_prev.get(replica_id)
        self._arrival_prev[replica_id] = arrivals
        if prev is None:
            delta = 0.0
        else:
            delta = arrivals if arrivals < prev else arrivals - prev
        self._arrival_total += delta
        sig = self._signal()
        if sig is not None:
            sig.observe(ts, self._arrival_total)

    def autoscale(self, now: float) -> Optional[Dict[str, Any]]:
        """One reconciliation pass of the scaling policy.  Four
        signals, pushed by the replicas: the averaged ongoing-request
        count (the sizing signal — desired = ceil(total/target)), the
        worst admission-queue age (leading SLO pressure: it climbs
        before any latency bound blows), the worst goodput ratio
        (trailing guard: a fleet already missing its objectives must
        not shrink), and — when upscale_slope_threshold is set — the
        arrival-rate slope (predictive: it moves before any queue even
        forms).  Pressure from any of them forces at least one step up
        from the current target and vetoes any scale-down this pass.
        Returns the applied decision dict, or None."""
        cfg = self.config.autoscaling_config
        if cfg is None or self.deleting:
            return None
        running = [r for r in self.replicas.values() if r.state == "RUNNING"]
        if not running:
            return None
        cutoff = now - cfg.look_back_period_s
        total = 0.0
        fresh = 0
        worst_age = 0.0
        worst_goodput: Optional[float] = None
        for r in running:
            m = self.metrics.get(r.replica_id)
            if m is not None and m[0] >= cutoff:
                fresh += 1
                total += m[1]
                if len(m) > 2 and m[2]:
                    worst_age = max(worst_age, m[2])
                if len(m) > 3 and m[3] is not None:
                    worst_goodput = (m[3] if worst_goodput is None
                                     else min(worst_goodput, m[3]))
        if fresh == 0:
            # No live signal at all — e.g. right after a controller
            # recovery, before the adopted fleet's first metric push.
            # Make NO decision (and leave any restored intent armed)
            # rather than sizing a busy fleet from an empty window,
            # which would read as "scale to min".
            return None
        desired = math.ceil(total / cfg.target_ongoing_requests)
        reason = "ongoing"
        pressure = False
        if (cfg.target_queue_age_s is not None
                and worst_age > cfg.target_queue_age_s):
            pressure, reason = True, "queue_age"
        elif (cfg.target_goodput is not None
              and worst_goodput is not None
              and worst_goodput < cfg.target_goodput):
            pressure, reason = True, "goodput"
        elif (cfg.upscale_slope_threshold is not None
              and self._arrival_signal is not None
              and self._arrival_signal.slope()
              > cfg.upscale_slope_threshold):
            # Predictive scale-up: the arrival RATE is still climbing,
            # so today's fleet will be undersized by the time a queue
            # forms — step up now, while queue age and goodput are
            # still clean.  Reactive reasons keep precedence: once a
            # queue exists it is the more honest signal.
            pressure, reason = True, "arrival_slope"
        current = self.target_replicas
        if pressure:
            desired = max(desired, current + 1)
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        if pressure and desired < current:
            # Scale-down vetoed while overloaded: the fleet is pinned
            # at max_replicas under pressure — exactly the incident the
            # flight recorder exists for.
            desired = current
            try:
                from ray_tpu.util import flight_recorder
                flight_recorder.trigger("autoscale_veto",
                                        reason_detail=reason,
                                        replicas=current)
            except Exception:
                pass
        if desired == current:
            self._scale_intent = None
            return None
        delay = (cfg.upscale_delay_s if desired > current
                 else cfg.downscale_delay_s)
        if self._scale_intent is None or (
            (self._scale_intent[0] > current) != (desired > current)
        ):
            self._scale_intent = (desired, now)
            return None
        if now - self._scale_intent[1] >= delay:
            self.target_replicas = desired
            self._scale_intent = None
            self.last_decision = {
                "direction": "up" if desired > current else "down",
                "from": current,
                "to": desired,
                "reason": reason,
                "ts": time.time(),
            }
            return self.last_decision
        return None


class ServeController:
    """The singleton control-plane actor."""

    def __init__(self):
        self._lock = threading.RLock()
        self._host = LongPollHost()
        self._deployments: Dict[Tuple[str, str], _DeploymentState] = {}
        self._routes: Dict[str, Tuple[str, str]] = {}  # prefix -> (app, ingress)
        self._app_ingress: Dict[str, str] = {}
        self._tm = _telemetry()
        self._reconcile_errors_seen: set = set()
        self._shutdown = threading.Event()
        # Crash recovery (the paper's durable-GCS keystone applied to
        # the serve control plane): every state mutation checkpoints
        # through the GCS StoreClient machinery, and a replacement
        # controller rebuilds itself from that checkpoint — re-census,
        # adoption, orphan sweep, rebroadcast — BEFORE the reconcile
        # loop starts, so routers only ever see tables that reflect a
        # verified fleet.  The epoch increments per generation; it
        # rides on every long_poll response so clients detect the
        # replacement and full-resync their snapshot ids.
        self._epoch = 1
        self._last_recovery = 0.0  # wall ts of last recovery (0 = never)
        self._last_ckpt_wall = 0.0
        self._self_actor_id = None  # resolved lazily by _fenced()
        self._ckpt = self._make_checkpointer()
        self._recover()
        # Persist the adopted state SYNCHRONOUSLY before serving: a
        # second crash inside the first debounce window would otherwise
        # recover from the previous generation's blob and reuse its
        # epoch — and an epoch collision means long-poll clients never
        # detect the replacement.
        try:
            with self._ckpt._save_lock:
                self._ckpt.save(self._checkpoint_tables())
        except Exception:
            pass
        self._ckpt.start_flusher(self._checkpoint_tables)
        threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile"
        ).start()

    # -- checkpointing -----------------------------------------------------

    def _fenced(self) -> bool:
        """True once this instance's actor shell has died.  A hard kill
        on a thread-mode actor cannot stop the instance's OWN daemon
        threads (reconcile loop, checkpoint flusher), so they check
        this fence and stand down — without it a SIGKILLed controller
        generation would keep mutating replicas and overwrite its
        successor's checkpoint.  Local (non-actor) instances never find
        a shell and never fence."""
        try:
            rt = api.runtime()
            if self._self_actor_id is None:
                for aid, shell in list(rt._actors.items()):
                    if shell.instance is self:
                        self._self_actor_id = aid
                        return False
                return False
            shell = rt._actors.get(self._self_actor_id)
            return shell is None or shell.dead
        except Exception:
            return False

    def _make_checkpointer(self):
        from ray_tpu.core.gcs_persistence import (
            FileStore,
            GcsPersistence,
            KvStoreClient,
            MirroredStore,
        )
        from ray_tpu.utils.config import get_config

        cfg = get_config()
        primary = KvStoreClient(api.runtime().kv, namespace=CKPT_NAMESPACE,
                                key=CKPT_KEY)
        mirrors = [FileStore(p.strip())
                   for p in cfg.serve_checkpoint_mirrors.split(",")
                   if p.strip()]
        store = MirroredStore(primary, mirrors) if mirrors else primary
        return GcsPersistence("", cfg.serve_checkpoint_flush_period_s,
                              store=store)

    def _checkpoint_tables(self) -> Dict[str, Any]:
        """Collect one checkpoint under the lock.  Plain-picklable end
        to end: DeploymentInfo (arbitrary user callables) rides as a
        cloudpickle sub-blob; actor handles, object refs and placement
        groups reduce to their ids.  Replica metrics are deliberately
        NOT persisted — a recovered autoscaler must size from live
        pushes, never from a dead generation's window."""
        import cloudpickle as _cp

        from ray_tpu.serve import audit as _audit

        if self._fenced():
            # Dead generation: refuse to collect, so the (best-effort)
            # flusher can never clobber the replacement controller's
            # checkpoint with this epoch's stale tables.
            raise RuntimeError("controller generation is fenced")
        with self._lock:
            deployments = []
            for (app, dep), st in sorted(self._deployments.items()):
                reps = []
                for rid in sorted(st.replicas):
                    r = st.replicas[rid]
                    reps.append({
                        "replica_id": rid,
                        "state": r.state,
                        "role": r.role,
                        "mesh_shape": r.mesh_shape,
                        "prefix_summary": r.prefix_summary,
                        "adapter_summary": r.adapter_summary,
                        "handle": r.handle,
                        # Only STARTING replicas need their creation
                        # ref back (recovery re-polls it); dropping the
                        # rest keeps resolved results out of the blob.
                        "creation_ref": (r.creation_ref
                                         if r.state == "STARTING"
                                         else None),
                        "members": list(r.members),
                        "pg": r.pg,
                    })
                if reps and _audit.corrupt(_audit.INJECT_STALE_CHECKPOINT):
                    reps = reps[:-1]  # checkpoint↔census drift
                intent = st._scale_intent
                deployments.append({
                    "app": app,
                    "name": dep,
                    "info": _cp.dumps(st.info),
                    "target_replicas": st.target_replicas,
                    "next_replica_idx": st.next_replica_idx,
                    "deleting": st.deleting,
                    "scale_intent_desired": (intent[0]
                                             if intent is not None
                                             else None),
                    "last_decision": (dict(st.last_decision)
                                      if st.last_decision else None),
                    "replicas": reps,
                })
            tables = {
                "ckpt_version": CKPT_VERSION,
                "epoch": self._epoch,
                "saved_at": time.time(),
                "deployments": deployments,
                "routes": dict(self._routes),
                "app_ingress": dict(self._app_ingress),
            }
        self._last_ckpt_wall = tables["saved_at"]
        return tables

    def _recover(self) -> None:
        """Rebuild state from the persisted checkpoint, if any: ping
        every checkpointed replica, adopt the live ones (DRAINING ones
        resume draining), drop unreachable ones onto the existing
        replacement path, hard-kill live replica actors the checkpoint
        has no record of, then rebroadcast routes + tables."""
        try:
            tables = self._ckpt.load()
        except Exception as e:
            log.warning("controller checkpoint unreadable (%r) — "
                        "starting fresh", e)
            return
        if not tables:
            return
        if tables.get("ckpt_version") != CKPT_VERSION:
            log.warning("controller checkpoint has unknown layout "
                        "version %r — starting fresh",
                        tables.get("ckpt_version"))
            return
        if tables.get("clean_shutdown"):
            # The previous generation exited deliberately (serve
            # shutdown): nothing to recover, keep only epoch continuity.
            self._epoch = int(tables.get("epoch", 0)) + 1
            return
        import cloudpickle as _cp

        self._epoch = int(tables.get("epoch", 0)) + 1
        self._last_recovery = time.time()
        now = time.monotonic()
        self._routes = dict(tables.get("routes") or {})
        self._app_ingress = dict(tables.get("app_ingress") or {})
        pings = []
        for d in tables.get("deployments") or ():
            try:
                info = _cp.loads(d["info"])
            except Exception as e:
                log.error("checkpointed deployment %s/%s is "
                          "unrecoverable (%r) — dropping it",
                          d.get("app"), d.get("name"), e)
                continue
            st = _DeploymentState(d["app"], info)
            st.target_replicas = int(d["target_replicas"])
            st.next_replica_idx = int(d["next_replica_idx"])
            st.deleting = bool(d["deleting"])
            if d.get("last_decision"):
                st.last_decision = dict(d["last_decision"])
            desired = d.get("scale_intent_desired")
            if (desired is not None
                    and st.config.autoscaling_config is not None):
                # Restart the intent timer from NOW: the fleet was just
                # re-censused, so letting a pre-crash countdown expire
                # immediately would fire a spurious scale event off a
                # dead generation's signals.
                st._scale_intent = (int(desired), now)
            self._deployments[(d["app"], d["name"])] = st
            for rd in d.get("replicas") or ():
                if rd.get("handle") is None:
                    continue
                ref = None
                # STARTING replicas may still be in __init__ (a ping
                # would queue behind it) — adopt them unpinged; their
                # creation ref resolves through _check_started exactly
                # as before the crash.  STOPPING ones are adopted
                # unpinged too: the stop path is idempotent.
                if rd["state"] in ("RUNNING", "DRAINING"):
                    try:
                        ref = rd["handle"].check_health.remote()
                    except Exception:
                        ref = None
                pings.append((st, rd, ref))
        adopted = 0
        adopted_ids = set()
        # Resolve the census pings only after ALL were fired — they
        # settle concurrently on the replicas' own actor threads.
        for st, rd, ref in pings:
            rid = rd["replica_id"]
            if rd["state"] in ("RUNNING", "DRAINING"):
                alive = False
                if ref is not None:
                    try:
                        api.get(ref, timeout=5.0)
                        alive = True
                    except Exception:
                        alive = False
                if not alive:
                    # Not adopted: the reconcile loop sees live <
                    # target and starts a replacement — the existing
                    # replica-death path.
                    log.warning("recovery: checkpointed replica %s is "
                                "unreachable — replacing it", rid)
                    continue
            r = _Replica(rid, rd["handle"], rd.get("creation_ref"))
            r.state = rd["state"]
            r.role = rd.get("role", "unified")
            r.mesh_shape = rd.get("mesh_shape", "")
            r.prefix_summary = rd.get("prefix_summary")
            r.adapter_summary = rd.get("adapter_summary")
            r.members = list(rd.get("members") or ())
            r.pg = rd.get("pg")
            r.last_health_check = now
            if r.state == "DRAINING":
                # Resume draining with a re-armed deadline (the drain
                # RPC was already delivered by the previous epoch).
                r.drain_deadline = (
                    now + st.config.graceful_shutdown_timeout_s + 30.0)
            st.replicas[rid] = r
            adopted_ids.add(r.handle._actor_id)
            for _rank, m in r.members:
                adopted_ids.add(m._actor_id)
            if r.state in ("RUNNING", "DRAINING", "STARTING"):
                adopted += 1
        killed = self._kill_stale_orphans(adopted_ids)
        # Rebuild + rebroadcast the full routing surface BEFORE the
        # reconcile loop starts: a router that resyncs against this
        # epoch must never observe an empty table.
        self._host.notify_changed(ROUTES_KEY, dict(self._routes))
        for st in self._deployments.values():
            self._broadcast(st)
        self._tm["restarts"].inc()
        if adopted:
            self._tm["orphans_adopted"].inc(adopted)
        if killed:
            self._tm["orphans_killed"].inc(killed)
        log.warning(
            "serve controller recovered from checkpoint: epoch=%d, "
            "%d deployment(s), %d replica(s) adopted, %d orphan(s) "
            "killed", self._epoch, len(self._deployments), adopted,
            killed)
        try:
            from ray_tpu.util import flight_recorder

            flight_recorder.trigger(
                "controller_recovery", detail=f"epoch={self._epoch}",
                adopted=adopted, orphans_killed=killed)
        except Exception:
            pass

    def _kill_stale_orphans(self, adopted_ids) -> int:
        """Hard-kill live replica/shard-member actors from the previous
        controller generation that the checkpoint has no record of
        (started inside the last flush window, or rows lost to a stale
        checkpoint copy).  They are invisible to reconciliation — left
        alone they would hold chips forever."""
        from ray_tpu.utils.test_utils import kill_actor_hard

        rt = api.runtime()
        killed = 0
        try:
            shells = list(rt._actors.items())
        except Exception:
            return 0
        for actor_id, shell in shells:
            try:
                if shell.dead or shell.cls.__name__ not in (
                        "ReplicaActor", "ShardMemberActor"):
                    continue
            except Exception:
                continue
            if actor_id in adopted_ids:
                continue
            try:
                kill_actor_hard(rt, actor_id)
                killed += 1
            except Exception:
                pass
        return killed

    # -- API ---------------------------------------------------------------

    def deploy_application(self, app_name: str, infos: List[DeploymentInfo],
                           route_prefix: Optional[str]) -> None:
        with self._lock:
            new_names = {i.name for i in infos}
            for (app, dep), st in list(self._deployments.items()):
                if app == app_name and dep not in new_names:
                    st.deleting = True
                    st.target_replicas = 0
            for info in infos:
                key = (app_name, info.name)
                st = self._deployments.get(key)
                if st is None or st.deleting:
                    self._deployments[key] = _DeploymentState(app_name, info)
                else:
                    st.apply_new_info(info)
                if info.is_ingress:
                    self._app_ingress[app_name] = info.name
            if route_prefix is not None:
                self._routes = {
                    p: t for p, t in self._routes.items() if t[0] != app_name
                }
                self._routes[route_prefix] = (
                    app_name, self._app_ingress[app_name]
                )
                self._host.notify_changed(ROUTES_KEY, dict(self._routes))
            self._ckpt.mark_dirty()

    def delete_application(self, app_name: str) -> None:
        with self._lock:
            for (app, _), st in self._deployments.items():
                if app == app_name:
                    st.deleting = True
                    st.target_replicas = 0
            self._routes = {
                p: t for p, t in self._routes.items() if t[0] != app_name
            }
            self._host.notify_changed(ROUTES_KEY, dict(self._routes))
            self._ckpt.mark_dirty()

    def get_ingress(self, app_name: str) -> str:
        with self._lock:
            name = self._app_ingress.get(app_name)
        if name is None:
            raise ValueError(f"no application named {app_name!r}")
        return name

    def long_poll(self, keys_to_ids: Dict[str, int]):
        # Non-blocking snapshot check: clients poll on a short cadence.
        # (The reference blocks in an asyncio handler, which holds no
        # thread; here a blocking listen would pin one controller pool
        # thread per subscriber, starving control RPCs at scale.)
        # The epoch rides on every response: a replacement controller's
        # snapshot ids restart at 1, so a client holding the previous
        # generation's large `seen` values would filter every update
        # forever — seeing the epoch move tells it to full-resync.
        return {"epoch": self._epoch,
                "updates": self._host.listen(keys_to_ids, timeout=0.0)}

    def record_autoscaling_metric(self, app_name: str, deployment_name: str,
                                  replica_id: str, ongoing: float,
                                  ts: float, queue_age: float = 0.0,
                                  goodput: Optional[float] = None,
                                  arrivals: Optional[float] = None) -> None:
        with self._lock:
            st = self._deployments.get((app_name, deployment_name))
            if st is None:
                return
            st.record_metric(replica_id, ongoing, ts, queue_age, goodput,
                             arrivals)
            # Live-load routing: broadcast rows carry each replica's
            # last-pushed ongoing count, so rebroadcast when the count
            # moved a whole request away from the broadcast one —
            # routers' p2c arm tracks real load without the controller
            # re-notifying every push.
            r = st.replicas.get(replica_id)
            if (r is not None and r.state in ("RUNNING", "DRAINING")
                    and abs(ongoing - r.bcast_ongoing) >= 1.0):
                self._broadcast(st)

    def record_prefix_summary(self, app_name: str, deployment_name: str,
                              replica_id: str, summary) -> None:
        """Replica push: its engine's prefix-cache routing summary
        changed.  Stored on the replica record and re-broadcast so
        every router's table row carries the fresh summary (the same
        long-poll channel that delivers membership changes)."""
        with self._lock:
            st = self._deployments.get((app_name, deployment_name))
            if st is None:
                return
            r = st.replicas.get(replica_id)
            if r is None or r.prefix_summary == summary:
                return
            r.prefix_summary = summary
            self._broadcast(st)

    def record_adapter_summary(self, app_name: str, deployment_name: str,
                               replica_id: str, summary) -> None:
        """Replica push: its engine's resident-adapter set changed.
        Same store-and-rebroadcast contract as record_prefix_summary —
        routers read the summary off their table row for
        adapter-affinity routing."""
        with self._lock:
            st = self._deployments.get((app_name, deployment_name))
            if st is None:
                return
            r = st.replicas.get(replica_id)
            if r is None or r.adapter_summary == summary:
                return
            r.adapter_summary = summary
            self._broadcast(st)

    def list_replicas(self) -> List[Dict[str, Any]]:
        """Replica inventory for `raytpu list replicas` (util/state.py):
        one row per replica, deterministic order (app, deployment,
        replica id).  Shard-group replicas carry their mesh shape
        ("dcn_tp=S x tp=T") and group membership (rank:actor pairs,
        rank 0 = the replica actor itself).  Every row carries the
        controller epoch + last-recovery wall time so an operator can
        see at a glance whether this fleet survived a control-plane
        crash (stable across calls — the determinism tests pin it)."""
        rows: List[Dict[str, Any]] = []
        with self._lock:
            last_recovery = (round(self._last_recovery, 3)
                             if self._last_recovery else "")
            for (app, dep), st in sorted(self._deployments.items()):
                actual = sum(1 for r in st.replicas.values()
                             if r.state == "RUNNING")
                last = st.last_decision
                autoscale = (
                    f"{last['direction']} {last['from']}->{last['to']} "
                    f"({last['reason']})" if last is not None else ""
                )
                for rid in sorted(st.replicas):
                    r = st.replicas[rid]
                    sg = st.config.shard_group
                    membership = ""
                    if sg is not None:
                        # hex[8:16]: the leading 4 bytes are the job id,
                        # identical for every actor — show the
                        # distinguishing slice.
                        parts = [f"0:{r.handle._actor_id.hex()[8:16]}"]
                        parts += [f"{rank}:{m._actor_id.hex()[8:16]}"
                                  for rank, m in r.members]
                        membership = ",".join(parts)
                    rows.append({
                        "app": app,
                        "deployment": dep,
                        "replica_id": rid,
                        "state": r.state,
                        "shard_group": sg.size if sg is not None else 0,
                        "mesh_shape": r.mesh_shape,
                        "members": membership,
                        "role": r.role,
                        "target_groups": st.target_replicas,
                        "actual_groups": actual,
                        "autoscale": autoscale,
                        "ctl_epoch": self._epoch,
                        "last_recovery": last_recovery,
                    })
        return rows

    def migration_targets(self, app_name: str, deployment_name: str,
                          role: Optional[str] = "decode",
                          exclude: Optional[List[str]] = None,
                          with_summary: bool = False,
                          with_load: bool = False) -> List[Tuple]:
        """RUNNING replicas of one deployment, for the KV-migration
        plane: a prefill replica asks here for its decode handoff
        target, a cold replica for warm peers to pull prefixes from.
        Deterministic (sorted by replica id).  Rows are
        ``(replica_id, handle)`` — plus the replica's latest prefix
        summary when ``with_summary`` (prefix migration picks the
        warmest peer by published hash count), or its last-pushed
        ongoing-request count when ``with_load`` (prefill→decode
        handoff picks the least-loaded decode replica)."""
        excluded = set(exclude or ())
        out: List[Tuple] = []
        with self._lock:
            st = self._deployments.get((app_name, deployment_name))
            if st is None:
                return []
            for rid in sorted(st.replicas):
                r = st.replicas[rid]
                if r.state != "RUNNING" or rid in excluded:
                    continue
                if role is not None and r.role != role:
                    continue
                if with_summary:
                    out.append((rid, r.handle, r.prefix_summary))
                elif with_load:
                    m = st.metrics.get(rid)
                    out.append((rid, r.handle,
                                float(m[1]) if m is not None else 0.0))
                else:
                    out.append((rid, r.handle))
        return out

    def drain_replica(self, app_name: str, deployment_name: str,
                      replica_id: str,
                      grace_s: Optional[float] = None) -> bool:
        """Deliver a preemption notice to one replica (the node-daemon
        maintenance-event path): flip it to DRAINING and send the drain
        RPC.  A replacement starts on the next reconcile pass while the
        draining replica stays in the route table.  Returns False for
        unknown or non-RUNNING replicas."""
        with self._lock:
            st = self._deployments.get((app_name, deployment_name))
            if st is None:
                raise ValueError(
                    f"no deployment {deployment_name!r} in app "
                    f"{app_name!r}")
            r = st.replicas.get(replica_id)
            if r is None:
                return False
            return self._mark_draining(st, r, grace_s=grace_s)

    def _mark_draining(self, st: _DeploymentState, r: _Replica, *,
                       grace_s: Optional[float] = None,
                       notify: bool = True) -> bool:
        if r.state != "RUNNING":
            return False
        r.state = "DRAINING"
        grace = (grace_s if grace_s is not None
                 else st.config.graceful_shutdown_timeout_s)
        # After the engine's grace expires it evicts what's left, so
        # in-flight work settles shortly after; the margin only bounds
        # a wedged replica.
        r.drain_deadline = time.monotonic() + grace + 30.0
        self._tm["drains"].inc(tags={"deployment": st.info.name})
        if notify:
            try:
                r.handle.drain.remote(grace)
            except Exception:
                r.state = "STOPPING"  # can't even reach it — replace
        # Routers read the draining flag off their table row (they
        # deprioritise draining replicas for NEW requests while keeping
        # them routable for retries) — tell them now, not at retirement.
        self._broadcast(st)
        return True

    def doctor(self, deep: bool = False,
               replica_id: Optional[str] = None) -> Dict[str, Any]:
        """Cluster invariant audit (the `raytpu doctor` backend): run
        the controller's own census↔broadcast consistency checks, fan
        the doctor RPC out to every RUNNING/DRAINING replica (or just
        ``replica_id``), and merge the per-process reports.  The
        merged report additionally carries ``census`` —
        {"app/deployment": [replica ids]} — so the caller can diff its
        local routers' tables against the same census snapshot."""
        from ray_tpu.serve import audit as _audit
        from ray_tpu.util import doctor as _doctor

        fns = []
        work: List[Tuple[str, Any]] = []
        census_by_key: Dict[str, List[str]] = {}
        with self._lock:
            # checkpoint↔census: flush the pending state synchronously,
            # read the persisted copy back through the store, and diff
            # it against the live census — catching a wedged or
            # corrupted checkpointer (the doctor.stale_checkpoint
            # injector drops a row to prove detection).  Under the same
            # lock as the census snapshot so the reconcile loop can't
            # move the fleet between the two reads.
            ckpt_rows: Dict[str, Dict[str, str]] = {}
            ckpt_err: Optional[str] = None
            try:
                with self._ckpt._save_lock:
                    self._ckpt.save(self._checkpoint_tables())
                blob = self._ckpt.store.load_blob()
                tables = (blob or {}).get("tables") or {}
                for d in tables.get("deployments") or ():
                    ckpt_rows[f"{d['app']}/{d['name']}"] = {
                        rd["replica_id"]: rd["state"]
                        for rd in d.get("replicas") or ()
                        if rd["state"] in ("RUNNING", "DRAINING")}
            except Exception as e:
                ckpt_err = repr(e)
            for (app, dep), st in sorted(self._deployments.items()):
                key = f"{app}/{dep}"
                census = [(rid, st.replicas[rid].state == "DRAINING")
                          for rid in sorted(st.replicas)
                          if st.replicas[rid].state
                          in ("RUNNING", "DRAINING")]
                census_by_key[key] = [rid for rid, _ in census]
                last = list(st.last_broadcast)
                fns.append((_audit.CENSUS_BROADCAST,
                            lambda k=key, c=census, t=last:
                            _audit.census_broadcast_checks(k, c, t)))
                fns.append((_audit.CHECKPOINT_CENSUS,
                            lambda k=key, c=census,
                            p=ckpt_rows.get(key), e=ckpt_err:
                            _audit.checkpoint_census_checks(k, c, p, e)))
                for rid, _draining in census:
                    if replica_id is not None and rid != replica_id:
                        continue
                    work.append((rid, st.replicas[rid].handle))
        reports = [_doctor.run_audit("controller", fns, deep=True)]
        for rid, handle in work:
            try:
                rep = api.get(handle.doctor.remote(deep))
            except Exception as e:
                rep = {"proc": rid, "checks_run": 0, "violations": 0,
                       "audit_seconds": 0.0, "checks": [],
                       "error": repr(e)}
            if rep is not None:  # None = callable has no doctor surface
                rep.setdefault("replica_id", rid)
                reports.append(rep)
        out = _doctor.merge_reports(reports, deep=deep)
        out["census"] = census_by_key
        return out

    def status(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {"applications": {}}
            for (app, dep), st in self._deployments.items():
                a = out["applications"].setdefault(
                    app, {"deployments": {}, "ingress": self._app_ingress.get(app)}
                )
                running = sum(
                    1 for r in st.replicas.values() if r.state == "RUNNING"
                )
                a["deployments"][dep] = {
                    "target_replicas": st.target_replicas,
                    "running_replicas": running,
                    "status": (
                        "DELETING" if st.deleting
                        else "HEALTHY" if running >= st.target_replicas
                        else "UPDATING"
                    ),
                }
            return out

    def get_routes(self) -> Dict[str, Tuple[str, str]]:
        with self._lock:
            return dict(self._routes)

    def graceful_shutdown(self) -> None:
        with self._lock:
            for st in self._deployments.values():
                st.deleting = True
                st.target_replicas = 0
            self._ckpt.mark_dirty()

    def _num_live(self) -> int:
        with self._lock:
            return sum(len(st.replicas) for st in self._deployments.values())

    def wait_for_drained(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._num_live() == 0:
                return True
            time.sleep(0.02)
        return self._num_live() == 0

    def stop_reconcile(self) -> None:
        """Stop the reconcile thread; called right before the controller
        actor is killed so no orphan loop keeps mutating state.  Also
        writes a clean-shutdown tombstone over the checkpoint: a
        DELIBERATE teardown must not be recovered from — the next
        controller generation starts fresh (keeping only epoch
        continuity) instead of resurrecting the torn-down app."""
        self._shutdown.set()
        try:
            self._ckpt.close(final_flush=False)
            with self._ckpt._save_lock:
                self._ckpt.save({
                    "ckpt_version": CKPT_VERSION,
                    "epoch": self._epoch,
                    "clean_shutdown": True,
                    "deployments": [],
                    "routes": {},
                    "app_ingress": {},
                })
        except Exception:
            pass

    # -- reconcile ---------------------------------------------------------

    def _reconcile_loop(self):
        while not self._shutdown.wait(RECONCILE_PERIOD_S):
            if self._fenced():
                # This generation's actor was hard-killed: stop
                # reconciling (a replacement controller owns the fleet
                # now) and stop the checkpoint flusher, WITHOUT the
                # clean-shutdown tombstone — the successor must
                # recover, not start fresh.
                self._shutdown.set()
                try:
                    self._ckpt.close(final_flush=False)
                except Exception:
                    pass
                return
            try:
                self._reconcile_once()
            except Exception:
                # A wedged reconcile loop must be visible, not silent:
                # count every swallowed error and log the traceback the
                # first time each distinct error appears (distinct =
                # the final exception line, so a repeating failure
                # doesn't flood the log at 20 Hz).
                self._tm["reconcile_errors"].inc()
                tb = traceback.format_exc()
                key = tb.strip().splitlines()[-1]
                if key not in self._reconcile_errors_seen:
                    self._reconcile_errors_seen.add(key)
                    log.error(
                        "serve reconcile loop error (repeats of this "
                        "error are counted in "
                        "raytpu_serve_reconcile_errors_total but not "
                        "re-logged):\n%s", tb)

    def _reconcile_once(self):
        now = time.monotonic()
        self._tm["ckpt_seq"].set(self._ckpt._seq)
        self._tm["ckpt_age"].set(
            max(0.0, time.time() - self._last_ckpt_wall)
            if self._last_ckpt_wall else 0.0)
        with self._lock:
            states = list(self._deployments.items())
        for key, st in states:
            with self._lock:
                intent_before = st._scale_intent
                decision = st.autoscale(now)
                if decision is not None:
                    self._tm["autoscale_decisions"].inc(
                        tags={"deployment": st.info.name,
                              "direction": decision["direction"],
                              "reason": decision.get("reason",
                                                     "ongoing")})
                if (st.config.autoscaling_config is not None
                        and not st.deleting):
                    self._tm["autoscale_target"].set(
                        st.target_replicas,
                        tags={"deployment": st.info.name})
                    self._tm["autoscale_actual"].set(
                        sum(1 for r in st.replicas.values()
                            if r.state == "RUNNING"),
                        tags={"deployment": st.info.name})
                if (decision is not None
                        or st._scale_intent is not intent_before):
                    # Intent state (armed/cleared/target moved) is part
                    # of the checkpoint — broadcast won't catch it.
                    self._ckpt.mark_dirty()
                self._check_started(st)
                self._check_health(st, now)
                changed = self._scale(st)
                if st.deleting and not st.replicas:
                    self._deployments.pop(key, None)
                    self._host.drop_key(replica_set_key(st.app_name, st.info.name))
                    self._ckpt.mark_dirty()
                    changed = False
            if changed:
                self._broadcast(st)

    def _check_started(self, st: _DeploymentState):
        rt = api.runtime()
        for r in st.replicas.values():
            if r.state == "STARTING" and rt.store.contains(r.creation_ref.id):
                try:
                    api.get(r.creation_ref)
                    r.state = "RUNNING"
                    self._maybe_warm_start(st, r)
                except Exception:
                    r.state = "STOPPING"  # constructor failed → replace

    def _maybe_warm_start(self, st: _DeploymentState, r: _Replica) -> None:
        """A freshly RUNNING replica of an autoscaled deployment starts
        with a cold prefix cache — every request it absorbs pays full
        prefill until the cache warms, exactly when the fleet is under
        the pressure that triggered the scale-up.  Kick off a one-shot
        pull_prefix_cache against the warmest surviving peer
        (kv_transfer's cold-start path) so the new capacity is useful
        immediately.  Fire-and-forget: a non-LLM callable ignores the
        method, a failed pull just means a cold start."""
        if st.config.autoscaling_config is None:
            return
        warm = any(
            p.prefix_summary for p in st.replicas.values()
            if p is not r and p.state in ("RUNNING", "DRAINING")
        )
        if not warm:
            return
        try:
            r.handle.handle_request.remote(
                "pull_prefix_cache", (),
                {"app_name": st.app_name,
                 "deployment_name": st.info.name,
                 "replica_id": r.replica_id},
                None,
            )
        except Exception:
            pass

    def _check_health(self, st: _DeploymentState, now: float):
        rt = api.runtime()
        for r in st.replicas.values():
            if r.state not in ("RUNNING", "DRAINING"):
                continue
            if r.health_ref is not None and rt.store.contains(r.health_ref.id):
                try:
                    verdict = api.get(r.health_ref)
                    if verdict == "DRAINING" and r.state == "RUNNING":
                        # Self-reported preemption notice (SIGTERM /
                        # node maintenance): the replica already began
                        # draining itself, so track it without sending
                        # another drain RPC.
                        self._mark_draining(st, r, notify=False)
                except Exception:
                    r.state = "STOPPING"  # unhealthy → replace
                r.health_ref = None
            elif (r.health_ref is None
                  and now - r.last_health_check
                  >= st.config.health_check_period_s):
                r.last_health_check = now
                r.health_ref = r.handle.check_health.remote()
                if r.members:
                    r.member_ping_refs = [
                        (rank, m.ping.remote()) for rank, m in r.members
                    ]
            if r.member_ping_refs and r.state in ("RUNNING", "DRAINING"):
                self._check_shard_members(st, r, rt)

    def _check_shard_members(self, st: _DeploymentState, r: _Replica, rt):
        """Resolve outstanding shard-member pings.  ANY member death is
        whole-replica failure: the group's mesh spans every member, so
        a lost member means lost collectives — rank 0 is hard-killed
        (sealing ActorDiedError into its live streams exactly as the
        lost link would on real hardware, which is what routes every
        in-flight request through the router's failover/replay path)
        and the group is replaced as one unit."""
        pending = []
        dead = False
        for rank, ref in r.member_ping_refs:
            if not rt.store.contains(ref.id):
                pending.append((rank, ref))
                continue
            try:
                api.get(ref)
            except Exception:
                dead = True
        r.member_ping_refs = pending
        if dead:
            from ray_tpu.utils.test_utils import kill_actor_hard

            log.warning(
                "shard group %s lost a member — failing the whole "
                "replica", r.replica_id)
            try:
                kill_actor_hard(rt, r.handle._actor_id)
            except Exception:
                pass
            r.state = "STOPPING"
            r.member_ping_refs = None

    def _scale(self, st: _DeploymentState) -> bool:
        changed = False
        running = [r for r in st.replicas.values() if r.state == "RUNNING"]
        # Retire draining replicas only once RUNNING capacity is back
        # at target AND their in-flight requests have settled: until
        # then they stay in the broadcast table, so a drain never dips
        # routable capacity, and killing the replica can't seal
        # ActorDiedError into a live stream.  The broadcast that drops
        # them is the same one that announces their replacement.
        if st.deleting or len(running) >= st.target_replicas:
            for r in st.replicas.values():
                if r.state != "DRAINING":
                    continue
                if st.deleting or self._drain_settled(r):
                    r.state = "STOPPING"
                    changed = True
        # Stop replicas marked STOPPING, and excess RUNNING ones.
        excess = len(running) + sum(
            1 for r in st.replicas.values() if r.state == "STARTING"
        ) - st.target_replicas
        auto_down = (st.config.autoscaling_config is not None
                     and not st.deleting)
        for r in sorted(running, key=lambda r: r.replica_id, reverse=True):
            if excess <= 0:
                break
            if auto_down:
                # Policy scale-down retires through the DRAINING path:
                # the replica finishes its in-flight streams (zero
                # router retries) and leaves the broadcast table only
                # once it has settled, so routable capacity never dips
                # below the new target mid-decision.
                if self._mark_draining(st, r):
                    changed = True
            else:
                r.state = "STOPPING"
            excess -= 1
        for r in list(st.replicas.values()):
            if r.state == "STOPPING":
                self._stop_replica(st, r)
                changed = True
        # Start missing replicas.
        live = [r for r in st.replicas.values()
                if r.state in ("STARTING", "RUNNING")]
        missing = st.target_replicas - len(live)
        for _ in range(max(0, missing)):
            self._start_replica(st)
            changed = True
        # Newly RUNNING replicas also need a broadcast.
        if any(r.state == "RUNNING" and not getattr(r, "_announced", False)
               for r in st.replicas.values()):
            changed = True
        return changed

    def _drain_settled(self, r: _Replica) -> bool:
        """True once a DRAINING replica has no in-flight requests, or
        its drain deadline passed (a wedged drain must not pin the
        replica forever).  Polled without blocking the reconcile loop:
        one outstanding num_ongoing_requests RPC at a time."""
        if (r.drain_deadline is not None
                and time.monotonic() >= r.drain_deadline):
            return True
        if r.ongoing_ref is None:
            try:
                r.ongoing_ref = r.handle.num_ongoing_requests.remote()
            except Exception:
                return True  # unreachable — nothing left to protect
            return False
        if not api.runtime().store.contains(r.ongoing_ref.id):
            return False
        ref, r.ongoing_ref = r.ongoing_ref, None
        try:
            return api.get(ref) == 0
        except Exception:
            return True

    def _start_replica(self, st: _DeploymentState):
        idx = st.next_replica_idx
        st.next_replica_idx += 1
        replica_id = f"{st.app_name}#{st.info.name}#{idx}"
        opts = dict(st.config.ray_actor_options)
        opts.setdefault("num_cpus", 0.1)
        cfg = st.config
        metrics_interval = (
            cfg.autoscaling_config.metrics_interval_s
            if cfg.autoscaling_config else 0.0
        )
        sg = cfg.shard_group
        members: List[Tuple[int, Any]] = []
        pg = None
        shard_kwarg = {}
        if sg is not None:
            # One placement group gang-reserves the whole group (one
            # bundle per member — on TPU each bundle is one host's
            # chips, ICI_CONTIGUOUS keeps the group on one slice
            # block); members rank 1..size-1 are ShardMemberActors,
            # rank 0 is the ReplicaActor itself so the router's
            # broadcast table naturally addresses the group's rank 0.
            from ray_tpu.core.placement_group import (
                PlacementGroupSchedulingStrategy,
                placement_group,
            )
            from ray_tpu.serve.replica import ShardMemberActor

            pg = placement_group(
                [dict(sg.bundle_resources) for _ in range(sg.size)],
                strategy=sg.placement_strategy,
                name=f"sg::{replica_id}",
            )
            member_cls = api.remote(ShardMemberActor)
            for rank in range(1, sg.size):
                m = member_cls.options(
                    num_cpus=0.1,
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg,
                        placement_group_bundle_index=rank,
                    ),
                ).remote(replica_id, rank, sg.size)
                members.append((rank, m))
            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=0,
            )
            shard_kwarg = {"shard_group": {
                "group_id": replica_id,
                "rank": 0,
                "size": sg.size,
                "tensor_parallel": sg.tensor_parallel,
                "dcn_collective": sg.dcn_collective,
                "member_ids": [m._actor_id.hex() for _, m in members],
            }}
        disagg_kwarg = {}
        role = "unified"
        dis = cfg.disagg
        if dis is not None:
            # Role by CENSUS of live prefill replicas, not by replica
            # index: a killed prefill replica's replacement takes the
            # prefill role again, so the split stays at target across
            # failovers.  (DRAINING replicas are not counted — their
            # replacement inherits the role immediately.)
            live_prefill = sum(
                1 for rep in st.replicas.values()
                if rep.role == "prefill"
                and rep.state in ("STARTING", "RUNNING"))
            role = ("prefill" if live_prefill < dis.prefill_replicas
                    else "decode")
            disagg_kwarg = {"disagg": {
                "role": role,
                "transfer": dis.transfer,
                "handoff_after_tokens": dis.handoff_after_tokens,
                "migration_timeout_s": dis.migration_timeout_s,
                "app_name": st.app_name,
                "deployment_name": st.info.name,
                "replica_id": replica_id,
            }}
        actor_cls = api.remote(ReplicaActor)
        handle = actor_cls.options(
            max_concurrency=cfg.max_ongoing_requests + 4, **opts
        ).remote(
            st.app_name, st.info.name, replica_id, st.info.func_or_class,
            st.info.init_args, st.info.init_kwargs, cfg.user_config,
            metrics_interval, **shard_kwarg, **disagg_kwarg,
        )
        r = _Replica(replica_id, handle, handle._creation_ref)
        r.members = members
        r.pg = pg
        r.role = role
        if sg is not None:
            r.mesh_shape = f"dcn_tp={sg.size} x tp={sg.tensor_parallel}"
            self._tm["shard_members"].set(
                sg.size, tags={"deployment": st.info.name,
                               "replica": replica_id})
        st.replicas[replica_id] = r

    def _stop_replica(self, st: _DeploymentState, r: _Replica):
        try:
            r.handle.prepare_for_shutdown.remote(
                st.config.graceful_shutdown_timeout_s
            )
            api.kill(r.handle, no_restart=True)
        except Exception:
            pass
        # Shard group: tear down the whole gang — surviving members
        # and the placement-group reservation go with rank 0.
        for _rank, m in r.members:
            try:
                api.kill(m, no_restart=True)
            except Exception:
                pass
        if r.pg is not None:
            from ray_tpu.core.placement_group import remove_placement_group

            try:
                remove_placement_group(r.pg)
            except Exception:
                pass
        if r.members or r.pg is not None:
            self._tm["shard_members"].set(
                0, tags={"deployment": st.info.name,
                         "replica": r.replica_id})
        st.replicas.pop(r.replica_id, None)
        st.metrics.pop(r.replica_id, None)

    def _broadcast(self, st: _DeploymentState):
        import inspect as _inspect

        # Async deployments route to handle_request_async (loop
        # interleaving on the replica); sync ones to handle_request
        # (thread pool) — see replica.py.
        target = st.info.func_or_class
        call = (getattr(target, "__call__", None)
                if _inspect.isclass(target) else target)
        is_async = (_inspect.iscoroutinefunction(call)
                    or _inspect.isasyncgenfunction(call))
        table = []
        for r in st.replicas.values():
            # DRAINING replicas stay routable (they finish in-flight
            # work and bounce new requests with PreemptedError, which
            # the router retries) until _scale retires them.
            if r.state in ("RUNNING", "DRAINING"):
                r._announced = True
                m = st.metrics.get(r.replica_id)
                ongoing = float(m[1]) if m is not None else 0.0
                r.bcast_ongoing = ongoing
                table.append(
                    (r.replica_id, r.handle, st.config.max_ongoing_requests,
                     is_async, r.prefix_summary, r.role, r.adapter_summary,
                     ongoing, r.state == "DRAINING")
                )
        from ray_tpu.serve import audit as _audit

        if table and _audit.corrupt(_audit.INJECT_BROADCAST):
            table = table[:-1]  # drop one row: census/broadcast desync
        # Record what was ACTUALLY announced (post-injection), so the
        # doctor's census_broadcast check diffs the real table against
        # the census rather than our intent.
        st.last_broadcast = [(row[0], bool(row[8])) for row in table]
        self._host.notify_changed(
            replica_set_key(st.app_name, st.info.name), table
        )
        # Anything worth telling the routers is worth persisting:
        # membership, drain flags, summaries and load all flow through
        # here, so the broadcast doubles as the checkpoint dirty edge.
        self._ckpt.mark_dirty()
