"""Per-request critical-path latency attribution (the waterfall).

Joins the three observability planes the serving stack already has —
the request-lifecycle ring (serve/request_events), tracer span walls
(util/tracing) and XLA program-cost estimates (util/xprof) — into one
per-request **waterfall** that partitions end-to-end wall clock into
named components:

    route           router admission → engine admission
    queue           engine admission → prefill start
    compile         overlap with first-dispatch XLA trace+compile walls
                    (excluded from the control-plane share: the victim
                    request is not blamed for cold-start compilation)
    prefill_device  device-cost estimate of the prompt's prefill flops
                    /bytes (clamped to the prefill phase wall)
    control_plane   the prefill-phase residual — dispatch, host-side
                    batching, scheduler overhead.  The ROADMAP item-6
                    baseline number.
    kv_transfer     decode-phase interludes where the stream was being
                    migrated to another replica (disagg handoff)
    retry_reprefill decode-phase interludes where a failed attempt was
                    being re-prefilled on a survivor
    decode_device   device-cost estimate of generated-token decode
    inter_step_gap  the decode-phase residual (host gaps between steps)

The partition is exact by construction — components always sum to the
stitched e2e wall — so the tier-1 invariant test can assert the sum
within float tolerance instead of hoping two clocks agree.

Device estimates come from ``xprof.ProgramRecord.cost_steps`` (the
token count the recorded cost covers): per-token device seconds =
``max(flops/peak_flops, bytes/peak_bw) / cost_steps`` against
``accelerator.chip_spec()`` peaks.  When a backend reports no cost
numbers the device components are 0 and the residuals stay honest.

Terminal requests feed the tier-1-pinned families
``raytpu_serve_request_overhead_seconds{component=...}`` and
``raytpu_serve_control_plane_share`` (engine-side, federated with a
``proc`` label like every serving family); the driver-side
``waterfall()`` join over federated rows backs
``GET /api/v0/requests/<id>/waterfall``, ``raytpu trace <id>`` and the
bench legs' ``dispatch_overhead`` block (``aggregate()``).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.serve import request_events as reqev
from ray_tpu.util import xprof

_TELEMETRY = None

COMPONENTS = ("route", "queue", "compile", "prefill_device",
              "control_plane", "kv_transfer", "retry_reprefill",
              "decode_device", "inter_step_gap")

# Program names whose recorded per-token device cost estimates each
# phase (first hit wins): unified engines dispatch serve.prefill /
# serve.decode, the mixed-batch engine dispatches serve.ragged for both
# (serve.ragged_spec is its speculative-verify variant — same shape,
# same per-token cost model).
_PREFILL_PROGRAMS = ("serve.prefill", "serve.ragged", "serve.ragged_spec")
_DECODE_PROGRAMS = ("serve.decode", "serve.ragged", "serve.ragged_spec")

_agg_lock = threading.Lock()
# (wall ts, waterfall dict) per observed terminal request — bounded;
# backs aggregate(since=) for the bench legs.
_observed: "collections.deque" = collections.deque(maxlen=4096)
_cum = {"control_plane": 0.0, "e2e_ex_compile": 0.0}


def _telemetry():
    """Attribution metric singletons (re-registered on refetch — see
    serve/llm_engine._telemetry for the registry-clear rationale)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "overhead": metrics.Histogram(
                "raytpu_serve_request_overhead_seconds",
                "Per-request waterfall component seconds (route / queue "
                "/ compile / prefill_device / control_plane / "
                "kv_transfer / retry_reprefill / decode_device / "
                "inter_step_gap); components sum to the request's e2e.",
                boundaries=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                            0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                            30.0],
                tag_keys=("component",),
            ),
            "share": metrics.Gauge(
                "raytpu_serve_control_plane_share",
                "Cumulative control-plane share of request e2e wall "
                "(compile excluded) over this process's observed "
                "requests — the ROADMAP item-6 baseline number.",
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


def clear() -> None:
    """Reset the aggregation state (tests)."""
    with _agg_lock:
        _observed.clear()
        _cum["control_plane"] = 0.0
        _cum["e2e_ex_compile"] = 0.0


# -- device-cost + compile-window helpers -----------------------------------

def _chip_peaks() -> Tuple[Optional[float], Optional[float]]:
    try:
        from ray_tpu.utils.accelerator import chip_spec
        spec = chip_spec()
        return spec.get("peak_flops"), spec.get("peak_hbm_bytes_per_s")
    except Exception:
        return None, None


def _per_token_device_s(program_names) -> float:
    """Analytic per-token device seconds for the first registered
    program in ``program_names`` with cost numbers: the roofline lower
    bound max(flops/peak_flops, bytes/peak_bw) over the tokens the
    recorded cost covers.  0.0 = no estimate (absent cost analysis)."""
    peak_flops, peak_bw = _chip_peaks()
    progs = xprof.programs()
    for name in program_names:
        rec = progs.get(name)
        if rec is None or not rec.cost_steps:
            continue
        bounds = []
        if rec.flops is not None and peak_flops:
            bounds.append(rec.flops / peak_flops)
        if rec.bytes_accessed is not None and peak_bw:
            bounds.append(rec.bytes_accessed / peak_bw)
        if bounds:
            return max(bounds) / rec.cost_steps
    return 0.0


def _overlap(windows: List[Tuple[float, float]],
             lo: float, hi: float) -> float:
    """Total coverage of [lo, hi] by the (possibly overlapping)
    windows, counted once."""
    if hi <= lo or not windows:
        return 0.0
    clipped = sorted((max(lo, a), min(hi, b)) for a, b in windows
                     if min(hi, b) > max(lo, a))
    total, cur_a, cur_b = 0.0, None, None
    for a, b in clipped:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total


def _compile_windows() -> List[Tuple[float, float]]:
    return [(rec.compiled_at - rec.compile_time_s, rec.compiled_at)
            for rec in xprof.programs().values()
            if rec.compiled_at is not None
            and rec.compile_time_s is not None and rec.compile_time_s > 0]


# -- the waterfall join -----------------------------------------------------

def _min_state(rows: List[Dict[str, Any]], state: str) -> Optional[float]:
    ts = [r["state_ts"][state] for r in rows
          if state in r.get("state_ts", {})]
    return min(ts) if ts else None


def waterfall(request_id: str,
              rows: Optional[List[Dict[str, Any]]] = None,
              ) -> Optional[Dict[str, Any]]:
    """Join every ring row for ``request_id`` (router + engine rows,
    across processes and attempts) into one waterfall dict, or None
    when the request is unknown or not yet terminal."""
    if rows is None:
        rows = [r for r in reqev.snapshot_rows()
                if r.get("request_id") == request_id]
    if not rows:
        return None
    st = reqev.stitch_request(request_id, rows=rows)
    t0, t_end = st["t_admitted"], st["t_terminal"]
    if t0 is None or t_end is None or t_end < t0:
        return None
    t_end = max(t_end, t0)

    router_rows = [r for r in rows
                   if str(r.get("engine", "")).startswith("router:")]
    eng_rows = [r for r in rows if r not in router_rows] or rows

    def clamp(t, lo, hi):
        return min(max(t, lo), hi)

    q0 = clamp(_min_state(eng_rows, reqev.QUEUED) or t0, t0, t_end)
    t_dec0 = clamp(_min_state(eng_rows, reqev.DECODING) or t_end,
                   q0, t_end)
    t_pre = clamp(_min_state(eng_rows, reqev.PREFILLING) or t_dec0,
                  q0, t_dec0)

    comp = {c: 0.0 for c in COMPONENTS}
    comp["route"] = q0 - t0
    comp["queue"] = t_pre - q0

    cw = _compile_windows()
    compile_p = _overlap(cw, t_pre, t_dec0)
    compile_d = _overlap(cw, t_dec0, t_end)
    comp["compile"] = compile_p + compile_d

    prompt_tokens = st["prompt_tokens"]
    prefix_hit = max((int(r.get("prefix_hit") or 0) for r in eng_rows),
                     default=0)
    per_tok_pre = _per_token_device_s(_PREFILL_PROGRAMS)
    p_budget = max(0.0, (t_dec0 - t_pre) - compile_p)
    comp["prefill_device"] = min(
        per_tok_pre * max(0, prompt_tokens - prefix_hit), p_budget)
    comp["control_plane"] = p_budget - comp["prefill_device"]

    # Decode-phase interludes: a resumed attempt's engine row enters
    # QUEUED after the stream already produced tokens elsewhere —
    # [its QUEUED, its DECODING] is time the stream spent off-device
    # being handed over.  Classified kv_transfer when the router saw a
    # planned MIGRATING handoff, retry_reprefill otherwise (failover).
    d_budget = max(0.0, (t_end - t_dec0) - compile_d)
    migrated = any(reqev.MIGRATING in r.get("state_ts", {})
                   for r in router_rows)
    interlude_kind = "kv_transfer" if migrated else "retry_reprefill"
    for r in eng_rows:
        sts = r.get("state_ts", {})
        rq = sts.get(reqev.QUEUED)
        if rq is None or rq <= t_dec0:
            continue  # the first attempt, not a resume
        w0 = clamp(rq, t_dec0, t_end)
        w1 = clamp(sts.get(reqev.DECODING, t_end), w0, t_end)
        dur = max(0.0, (w1 - w0) - _overlap(cw, w0, w1))
        dur = min(dur, d_budget)
        comp[interlude_kind] += dur
        d_budget -= dur

    # Speculative decoding emits several tokens per verify step: the
    # device ran one step per ROUND for those, so the per-step cost
    # multiplies generated - accepted (each round = 1 step emitting
    # accepted_i + 1 tokens), keeping decode_device + inter_step_gap
    # an exact partition of the decode wall under multi-token bursts.
    spec_acc = max((int(r.get("spec_accepted") or 0) for r in eng_rows),
                   default=0)
    per_tok_dec = _per_token_device_s(_DECODE_PROGRAMS)
    comp["decode_device"] = min(
        per_tok_dec * max(0, st["generated_tokens"] - spec_acc),
        d_budget)
    comp["inter_step_gap"] = d_budget - comp["decode_device"]

    e2e = t_end - t0
    ex_compile = max(e2e - comp["compile"], 1e-12)
    return {
        "request_id": request_id,
        "state": st["state"],
        "t_start": t0,
        "t_end": t_end,
        "e2e_s": e2e,
        "ttft_s": st["ttft_s"],
        "attempts": st["attempts"],
        "prompt_tokens": prompt_tokens,
        "generated_tokens": st["generated_tokens"],
        "components": comp,
        "control_plane_share": comp["control_plane"] / ex_compile,
        "compile_excluded": comp["compile"] > 0.0,
        "procs": sorted({str(r.get("proc", "driver")) for r in rows}),
    }


# -- terminal observation (engine-side) + bench aggregation -----------------

def observe_terminal(request_id: str,
                     rows: Optional[List[Dict[str, Any]]] = None,
                     ) -> Optional[Dict[str, Any]]:
    """Record a just-terminal request into the metric families and the
    bench aggregation window.  Called by the engine at terminal with
    its local ring rows (no router row there: route=0 — the router-
    inclusive join stays available driver-side via ``waterfall``)."""
    if rows is None:
        rows = [r for r in reqev.snapshot_rows(local_only=True)
                if r.get("request_id") == request_id]
    wf = waterfall(request_id, rows=rows)
    if wf is None:
        return None
    tm = _telemetry()
    for c in COMPONENTS:
        tm["overhead"].observe(wf["components"][c],
                               tags={"component": c})
    with _agg_lock:
        _observed.append((time.time(), wf))
        _cum["control_plane"] += wf["components"]["control_plane"]
        _cum["e2e_ex_compile"] += max(
            wf["e2e_s"] - wf["components"]["compile"], 0.0)
        share = (_cum["control_plane"]
                 / max(_cum["e2e_ex_compile"], 1e-12))
    tm["share"].set(share)
    return wf


def aggregate(since: float = 0.0) -> Optional[Dict[str, Any]]:
    """The bench legs' ``dispatch_overhead`` block: mean component
    seconds + aggregate control-plane share over requests observed at
    wall time >= ``since``.  None when nothing was observed (the block
    is absent-not-zero on legs that skip it)."""
    with _agg_lock:
        wfs = [wf for ts, wf in _observed if ts >= since]
    if not wfs:
        return None
    n = len(wfs)
    comps = {c: sum(wf["components"][c] for wf in wfs) / n
             for c in COMPONENTS}
    cp = sum(wf["components"]["control_plane"] for wf in wfs)
    ex = sum(max(wf["e2e_s"] - wf["components"]["compile"], 0.0)
             for wf in wfs)
    return {
        "requests": n,
        "components": comps,
        "control_plane_share": min(cp / max(ex, 1e-12), 1.0),
        "e2e_mean_s": sum(wf["e2e_s"] for wf in wfs) / n,
    }
