"""@serve.batch — dynamic request batching.

Parity with the reference (ray: python/ray/serve/batching.py — @serve.batch
:65, _BatchQueue:337): concurrent callers' single items are grouped into
one call of the wrapped function (which takes a list and returns a list
of equal length).  Effective with max_ongoing_requests > 1 so several
requests are in the replica simultaneously.
"""

from __future__ import annotations

import functools
import queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"batch-{getattr(fn, '__name__', 'fn')}",
        )
        self._thread.start()

    def submit(self, item: Any) -> Future:
        fut: Future = Future()
        self._q.put((item, fut))
        return fut

    def _loop(self):
        while True:
            item, fut = self._q.get()
            batch = [(item, fut)]
            # Wait up to batch_wait_timeout_s to fill the batch
            # (parity: _BatchQueue wait loop).
            import time

            deadline = time.monotonic() + self._wait
            while len(batch) < self._max:
                remaining = deadline - time.monotonic()
                try:
                    batch.append(
                        self._q.get(timeout=max(0.0, remaining))
                        if remaining > 0 else self._q.get_nowait()
                    )
                except queue.Empty:
                    break
            items = [b[0] for b in batch]
            try:
                results = self._fn(items)
                if len(results) != len(items):
                    raise ValueError(
                        f"batched function returned {len(results)} results "
                        f"for {len(items)} inputs"
                    )
                for (_, f), r in zip(batch, results):
                    f.set_result(r)
            except Exception as e:
                for _, f in batch:
                    f.set_exception(e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped fn must take a list of items; callers pass
    one item and block for their element of the result."""

    def wrap(fn: Callable):
        queues: dict = {}
        lock = threading.Lock()

        @functools.wraps(fn)
        def wrapper(*call_args):
            # Support bound methods: (self, item) or plain (item,).
            if len(call_args) == 2:
                owner, item = call_args
                bound = functools.partial(fn, owner)
                key = id(owner)
            elif len(call_args) == 1:
                item = call_args[0]
                bound = fn
                key = None
            else:
                raise TypeError("@serve.batch functions take a single item")
            with lock:
                bq = queues.get(key)
                if bq is None:
                    bq = queues[key] = _BatchQueue(
                        bound, max_batch_size, batch_wait_timeout_s
                    )
            return bq.submit(item).result()

        wrapper._is_serve_batch = True  # type: ignore[attr-defined]
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
