"""@serve.batch — dynamic request batching.

Parity with the reference (ray: python/ray/serve/batching.py — @serve.batch
:65, _BatchQueue:337): concurrent callers' single items are grouped into
one call of the wrapped function (which takes a list and returns a list
of equal length).  Effective with max_ongoing_requests > 1 so several
requests are in the replica simultaneously.
"""

from __future__ import annotations

import functools
import inspect
import queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

from ray_tpu.util import tracing

_TELEMETRY = None


def _telemetry():
    """@serve.batch metric singletons (re-registered on refetch — see
    llm_engine._telemetry for the registry-clear rationale)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "batch_size": metrics.Histogram(
                "raytpu_serve_batch_size",
                "Items flushed per @serve.batch call.",
                boundaries=[1, 2, 4, 8, 16, 32, 64, 128],
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


class _BatchQueue:
    """One flusher thread per (function, owner).  The owner is held only
    weakly: when the replica's user object is collected, the thread
    exits and the queue dies with it (no leak across replica churn)."""

    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float,
                 owner: Any = None):
        import weakref

        self._fn = fn
        self._owner_ref = (weakref.ref(owner) if owner is not None else None)
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._q: "queue.Queue" = queue.Queue()
        self._tm = _telemetry()
        self._loop_obj = None  # lazy per-thread loop for async handlers
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"batch-{getattr(fn, '__name__', 'fn')}",
        )
        self._thread.start()

    def submit(self, item: Any) -> Future:
        fut: Future = Future()
        # The caller's span context rides with the item: batches flush
        # on the flusher thread, so formation/execution spans parent to
        # the FIRST item's request rather than floating rootless.
        self._q.put((item, fut, tracing.capture_context()))
        return fut

    def _bound_fn(self) -> Optional[Callable]:
        if self._owner_ref is None:
            return self._fn
        owner = self._owner_ref()
        if owner is None:
            return None
        return functools.partial(self._fn, owner)

    def _event_loop(self):
        if self._loop_obj is None:
            import asyncio

            self._loop_obj = asyncio.new_event_loop()
        return self._loop_obj

    def _loop(self):
        while True:
            try:
                item, fut, ctx = self._q.get(timeout=5.0)
            except queue.Empty:
                if self._owner_ref is not None and self._owner_ref() is None:
                    if self._loop_obj is not None:
                        self._loop_obj.close()  # release epoll/pipe fds
                    return  # owner collected — exit
                continue
            batch = [(item, fut, ctx)]
            # Wait up to batch_wait_timeout_s to fill the batch
            # (parity: _BatchQueue wait loop).
            import time

            form_start = time.time()
            deadline = time.monotonic() + self._wait
            while len(batch) < self._max:
                remaining = deadline - time.monotonic()
                try:
                    batch.append(
                        self._q.get(timeout=max(0.0, remaining))
                        if remaining > 0 else self._q.get_nowait()
                    )
                except queue.Empty:
                    break
            items = [b[0] for b in batch]
            self._tm["batch_size"].observe(len(items))
            tracing.record_span(
                "serve.batch_form", form_start, time.time(), ctx=ctx,
                attributes={"batch_size": len(items)})
            try:
                bound = self._bound_fn()
                if bound is None:
                    raise RuntimeError("batch owner was garbage-collected")
                with tracing.span("serve.batch_call", ctx=ctx,
                                  attributes={"batch_size": len(items)}):
                    results = bound(items)
                    if inspect.iscoroutine(results):
                        # async batched fns are supported (parity: the
                        # reference's @serve.batch wraps async handlers).
                        # One persistent loop per batch thread: handlers
                        # may cache loop-bound state across batches.
                        results = self._event_loop().run_until_complete(
                            results
                        )
                if len(results) != len(items):
                    raise ValueError(
                        f"batched function returned {len(results)} results "
                        f"for {len(items)} inputs"
                    )
                for (_, f, _c), r in zip(batch, results):
                    f.set_result(r)
            except Exception as e:
                for _, f, _c in batch:
                    f.set_exception(e)


class _BatchedCallable:
    """The @serve.batch wrapper as a picklable descriptor: runtime state
    (lock, queues, flusher threads) is rebuilt fresh on unpickle, so a
    deployment class carrying a batched method ships cleanly to replica
    worker processes (closures capturing a threading.Lock cannot)."""

    _is_serve_batch = True

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        functools.update_wrapper(self, fn)
        self._init_runtime_state()

    def _init_runtime_state(self) -> None:
        self._lock = threading.Lock()
        self._shared: List[Optional[_BatchQueue]] = [None]  # unbound case
        self._attr = f"__batch_queue_{self._fn.__name__}"
        # Fallback for owners that reject setattr/weakref (__slots__,
        # frozen dataclasses): strong id-keyed map, the pre-weakref
        # behavior (leaks across owner churn, but only for such classes).
        self._rigid_queues: dict = {}

    def __reduce__(self):
        return (_rebuild_batched, (self._fn, self._max, self._wait))

    def __get__(self, obj, objtype=None):
        # Descriptor protocol: instance.method binds the owner like a
        # normal function attribute would.
        if obj is None:
            return self
        return functools.partial(self.__call__, obj)

    def __call__(self, *call_args):
        # Support bound methods: (self, item) or plain (item,).
        if len(call_args) == 2:
            owner, item = call_args
            with self._lock:
                bq = getattr(owner, self._attr, None) \
                    or self._rigid_queues.get(id(owner))
                if bq is None:
                    # Probe attribute assignment BEFORE starting a
                    # queue (its flusher thread would leak if setattr
                    # failed afterwards).
                    try:
                        setattr(owner, self._attr, None)
                        bq = _BatchQueue(
                            self._fn, self._max, self._wait, owner=owner,
                        )
                        setattr(owner, self._attr, bq)
                    except (AttributeError, TypeError):
                        bq = _BatchQueue(
                            functools.partial(self._fn, owner),
                            self._max, self._wait,
                        )
                        self._rigid_queues[id(owner)] = bq
        elif len(call_args) == 1:
            item = call_args[0]
            with self._lock:
                if self._shared[0] is None:
                    self._shared[0] = _BatchQueue(
                        self._fn, self._max, self._wait
                    )
                bq = self._shared[0]
        else:
            raise TypeError("@serve.batch functions take a single item")
        return bq.submit(item).result()


def _rebuild_batched(fn, max_batch_size, batch_wait_timeout_s):
    return _BatchedCallable(fn, max_batch_size, batch_wait_timeout_s)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped fn must take a list of items; callers pass
    one item and block for their element of the result."""

    def wrap(fn: Callable):
        return _BatchedCallable(fn, max_batch_size, batch_wait_timeout_s)

    if _fn is not None:
        return wrap(_fn)
    return wrap
