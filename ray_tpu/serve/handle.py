"""DeploymentHandle / DeploymentResponse — the composition API.

Parity with the reference (ray: python/ray/serve/handle.py —
DeploymentHandle:297, DeploymentResponse:795): ``handle.remote(...)``
returns a response future; responses can be passed straight into other
handles' ``.remote(...)`` calls (the downstream replica receives the
resolved value), mirroring model-composition graphs.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from ray_tpu.core import api
from ray_tpu.core.object_ref import ObjectRef

_routers_lock = threading.Lock()
_routers: Dict[Tuple[str, str], Any] = {}


def _router_for(app_name: str, deployment_name: str):
    from ray_tpu.serve.router import Router

    key = (app_name, deployment_name)
    with _routers_lock:
        r = _routers.get(key)
        if r is None:
            r = _routers[key] = Router(app_name, deployment_name)
        return r


def _shutdown_routers() -> None:
    with _routers_lock:
        for r in _routers.values():
            r.stop()
        _routers.clear()


class DeploymentResponse:
    """Future for one request (parity: serve DeploymentResponse)."""

    def __init__(self, ref: ObjectRef, resubmit=None):
        self._ref = ref
        self._resubmit = resubmit

    def result(self, timeout_s: Optional[float] = None) -> Any:
        from ray_tpu.core.exceptions import ActorDiedError

        # A replica can die between assignment and execution (downscale,
        # health replacement).  The request never started, so retrying on
        # a live replica is safe (parity: serve router replica retries).
        # The resubmit closure excludes every replica already observed
        # dead, so retries can't land on the same one.
        attempts = 3 if self._resubmit is not None else 1
        for attempt in range(attempts):
            try:
                return api.get(self._ref, timeout=timeout_s)
            except ActorDiedError:
                if attempt == attempts - 1:
                    raise
                self._ref = self._resubmit()

    def __await__(self):
        """Awaitable inside async replicas (parity: serve
        DeploymentResponse.__await__): the blocking get runs on the
        loop's default executor, so concurrent requests on one async
        replica interleave while awaiting downstream deployments."""
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, self.result)
        return fut.__await__()

    def _to_object_ref(self) -> ObjectRef:
        return self._ref

    def __reduce__(self):
        # A response travels as its underlying ref; the runtime resolves
        # refs in task args, so downstream replicas see the value.
        return (DeploymentResponse, (self._ref,))


class DeploymentHandle:
    """Client-side handle to a deployment (one router per process per
    deployment, shared across handle copies)."""

    def __init__(self, deployment_name: str, app_name: str,
                 method_name: str = "__call__",
                 assign_timeout_s: Optional[float] = None,
                 multiplexed_model_id: str = ""):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name
        # None = wait for a free replica slot indefinitely (backpressure,
        # the reference's behavior); a number bounds the wait.
        self._assign_timeout_s = assign_timeout_s
        self._multiplexed_model_id = multiplexed_model_id

    def options(self, *, method_name: Optional[str] = None,
                assign_timeout_s: Optional[float] = None,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name or self._method_name,
            (assign_timeout_s if assign_timeout_s is not None
             else self._assign_timeout_s),
            (multiplexed_model_id if multiplexed_model_id is not None
             else self._multiplexed_model_id),
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # handle.method.remote(...) sugar (parity: handle method access)
        return DeploymentHandle(self.deployment_name, self.app_name, name,
                                self._assign_timeout_s,
                                self._multiplexed_model_id)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        args = tuple(self._unwrap(a) for a in args)
        kwargs = {k: self._unwrap(v) for k, v in kwargs.items()}
        router = _router_for(self.app_name, self.deployment_name)
        method = self._method_name
        timeout = self._assign_timeout_s
        model_id = self._multiplexed_model_id
        dead: set = set()
        last = [None]

        def submit() -> ObjectRef:
            if last[0] is not None:
                dead.add(last[0])
            ref, replica_id = router.assign(
                method, args, kwargs, timeout=timeout, exclude=dead,
                model_id=model_id,
            )
            last[0] = replica_id
            return ref

        return DeploymentResponse(submit(), resubmit=submit)

    @staticmethod
    def _unwrap(value: Any) -> Any:
        # Pass the underlying ref; the actor runtime resolves refs in args
        # before execution (parity: response-to-upstream-arg resolution).
        if isinstance(value, DeploymentResponse):
            return value._to_object_ref()
        return value

    def __repr__(self):
        return (f"DeploymentHandle({self.app_name}/{self.deployment_name}"
                f".{self._method_name})")

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self.deployment_name, self.app_name, self._method_name,
             self._assign_timeout_s, self._multiplexed_model_id),
        )
