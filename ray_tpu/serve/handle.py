"""DeploymentHandle / DeploymentResponse — the composition API.

Parity with the reference (ray: python/ray/serve/handle.py —
DeploymentHandle:297, DeploymentResponse:795): ``handle.remote(...)``
returns a response future; responses can be passed straight into other
handles' ``.remote(...)`` calls (the downstream replica receives the
resolved value), mirroring model-composition graphs.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import api
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.serve import request_events as _reqev

_routers_lock = threading.Lock()
_routers: Dict[Tuple[str, str], Any] = {}


def _is_death(err: BaseException) -> bool:
    """The replica process is gone: ActorDiedError directly (queued
    calls sealed on death), or a TaskError whose cause is NOT an
    Exception — the serve loop seals the in-flight call with the raw
    BaseException that killed the actor (see _after_item_error), so a
    non-Exception cause is the in-flight face of the same death."""
    from ray_tpu.core.exceptions import ActorDiedError, TaskError

    if isinstance(err, ActorDiedError):
        return True
    return (isinstance(err, TaskError)
            and not isinstance(getattr(err, "cause", None), Exception))


def _migration_handoff(err: BaseException):
    """The MigrationHandoff inside an attempt's outcome, if any —
    raised directly (local engine) or riding a TaskError from the
    replica.  A handoff is a SUCCESSFUL prefill attempt whose KV pages
    landed on a decode replica; the stream resumes there."""
    from ray_tpu.core.exceptions import TaskError
    from ray_tpu.serve.kv_transfer import MigrationHandoff

    if isinstance(err, MigrationHandoff):
        return err
    if (isinstance(err, TaskError)
            and isinstance(getattr(err, "cause", None), MigrationHandoff)):
        return err.cause
    return None


def _shed_error(err: BaseException):
    """The ShedError inside an attempt's outcome, if any — raised
    directly (local engine) or riding a TaskError from the replica.
    A shed is clean admission-control backpressure: no attempt ran, so
    the handle fails fast with the unwrapped error instead of burning
    its retry budget re-enqueueing onto the same overloaded queue."""
    from ray_tpu.core.exceptions import ShedError, TaskError

    if isinstance(err, ShedError):
        return err
    if (isinstance(err, TaskError)
            and isinstance(getattr(err, "cause", None), ShedError)):
        return err.cause
    return None


def _is_retriable(err: BaseException) -> bool:
    """Safe to re-enqueue the request on a surviving replica: the
    replica died (the work is lost, not duplicated) or it preempted the
    request cooperatively (PreemptedError — raised locally by a
    draining engine, or riding a TaskError from the replica)."""
    from ray_tpu.core.exceptions import PreemptedError, TaskError

    if _is_death(err):
        return True
    if isinstance(err, PreemptedError):
        return True
    return (isinstance(err, TaskError)
            and isinstance(getattr(err, "cause", None), PreemptedError))


def _router_for(app_name: str, deployment_name: str):
    from ray_tpu.serve.router import Router

    key = (app_name, deployment_name)
    with _routers_lock:
        r = _routers.get(key)
        if r is None:
            r = _routers[key] = Router(app_name, deployment_name)
        return r


def _shutdown_routers() -> None:
    with _routers_lock:
        for r in _routers.values():
            r.stop()
        _routers.clear()


class DeploymentResponse:
    """Future for one request (parity: serve DeploymentResponse)."""

    def __init__(self, ref: ObjectRef, resubmit=None):
        self._ref = ref
        self._resubmit = resubmit

    def result(self, timeout_s: Optional[float] = None) -> Any:
        from ray_tpu.core.exceptions import (ActorDiedError, PreemptedError,
                                             TaskError)

        # A replica can die between assignment and execution (downscale,
        # health replacement) or preempt the request cooperatively while
        # draining.  Either way the work is lost, not duplicated, so
        # resubmitting on a live replica is safe (parity: serve router
        # replica retries).  The resubmit closure excludes every replica
        # already observed dead, so retries can't land on the same one.
        # ``timeout_s`` is ONE deadline shared across every attempt —
        # not a per-attempt allowance — and attempts are spaced by
        # capped exponential backoff with jitter so a fleet of callers
        # doesn't stampede the surviving replicas in lockstep.
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        attempts = 3 if self._resubmit is not None else 1
        backoff = 0.05
        for attempt in range(attempts):
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                return api.get(self._ref, timeout=remaining)
            except (ActorDiedError, PreemptedError, TaskError) as err:
                retriable = (
                    isinstance(err, (ActorDiedError, PreemptedError))
                    or isinstance(getattr(err, "cause", None),
                                  PreemptedError))
                if (not retriable or attempt == attempts - 1
                        or (deadline is not None
                            and time.monotonic() >= deadline)):
                    raise
                # Half-fixed + half-jitter: spreads a stampede of
                # retrying callers without ever collapsing the spacing
                # to ~0 (a replacement replica needs real time to start).
                delay = backoff / 2.0 + random.uniform(0.0, backoff / 2.0)
                backoff = min(backoff * 2.0, 1.0)
                if deadline is not None:
                    delay = min(delay,
                                max(0.0, deadline - time.monotonic()))
                time.sleep(delay)
                self._ref = self._resubmit()

    def __await__(self):
        """Awaitable inside async replicas (parity: serve
        DeploymentResponse.__await__): the blocking get runs on the
        loop's default executor, so concurrent requests on one async
        replica interleave while awaiting downstream deployments."""
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, self.result)
        return fut.__await__()

    def _to_object_ref(self) -> ObjectRef:
        return self._ref

    def __reduce__(self):
        # A response travels as its underlying ref; the runtime resolves
        # refs in task args, so downstream replicas see the value.
        return (DeploymentResponse, (self._ref,))


class DeploymentResponseGenerator:
    """Streaming response with mid-stream failover (parity: serve's
    DeploymentResponseGenerator, plus the failover the reference leaves
    to the application).  Iterating yields items as the replica
    generates them.  When the current attempt dies (replica hard-killed)
    or is preempted (replica draining), the request is re-enqueued on a
    surviving replica under a per-request retry budget and the shared
    deadline, with capped-exponential jittered backoff between attempts.

    For LLM payloads (first positional arg a dict with a ``tokens``
    prompt) the retry resumes from ``prompt + generated_prefix`` — one
    re-prefill of the continuation, no token re-generated, no token
    lost: the replica seals every generated token before the failure
    surfaces, so the delivered prefix IS the generated prefix, and
    greedy decoding makes the continuation bit-identical to the
    uninterrupted stream.  For any other payload the retry replays the
    stream and skips the already-delivered prefix (deterministic
    streams only), so consumers still see each item exactly once."""

    def __init__(self, router, method_name: str, args: tuple, kwargs: dict,
                 *, assign_timeout_s: Optional[float] = None,
                 model_id: str = "", max_retries: int = 3,
                 total_timeout_s: Optional[float] = None):
        self._router = router
        self._method_name = method_name
        self._args = args
        self._kwargs = kwargs
        self._assign_timeout_s = assign_timeout_s
        self._model_id = model_id
        self._max_retries = max_retries
        self._total_timeout_s = total_timeout_s
        # One identity for every attempt: the id is minted once and
        # re-sent on retries, so the engine rings, the router ring,
        # spans and log lines all tell one request's story.
        self.request_id = _reqev.get_request_id() or _reqev.new_request_id()
        self._delivered: List[Any] = []
        self._iter = None
        # Disaggregated-serving handoff state: once a prefill replica
        # migrates this stream's KV pages, resumed attempts carry
        # ``_disagg_resumed`` (so prefill replicas serve them instead
        # of handing off again) and prefer the decode replica the
        # pages landed on.
        self._migrated = False
        self._prefer_replica: Optional[str] = None

    @property
    def delivered(self) -> List[Any]:
        """Items yielded so far (the generated prefix for LLM streams)."""
        return list(self._delivered)

    def __iter__(self):
        return self

    def __next__(self):
        if self._iter is None:
            self._iter = self._run()
        return next(self._iter)

    def result(self, timeout_s: Optional[float] = None) -> List[Any]:
        """Drain the stream and return every item (LLM: the full list
        of generated tokens).  ``timeout_s`` installs the shared
        cross-attempt deadline if none was set at creation."""
        if timeout_s is not None and self._total_timeout_s is None:
            self._total_timeout_s = timeout_s
        for _ in self:
            pass
        return list(self._delivered)

    # -- attempt loop ------------------------------------------------------

    def _continuation_args(self):
        """Args for a resumed attempt.  Returns (args, skip): LLM dict
        payloads get prompt+prefix spliced in (skip 0); anything else
        replays verbatim and skips the delivered prefix.  args=None
        means the continuation has nothing left to generate.

        Prefix-resumed failover: the spliced payload re-enters the
        router's cache-aware selection (assign_streaming matches its
        ``tokens`` against replica prefix summaries), so with
        EngineConfig.prefix_cache the retry lands on a survivor
        holding the shared prefix and re-prefills only the cold tail —
        the replay's full re-prefill collapses to the uncached suffix
        plus the delivered tokens."""
        if not self._delivered:
            return self._args, 0
        first = self._args[0] if self._args else None
        if isinstance(first, dict) and "tokens" in first:
            payload = dict(first)
            payload["tokens"] = list(first["tokens"]) + \
                [t for t in self._delivered]
            if payload.get("max_new_tokens") is not None:
                remaining = (int(payload["max_new_tokens"])
                             - len(self._delivered))
                if remaining <= 0:
                    return None, 0
                payload["max_new_tokens"] = remaining
            payload["request_id"] = self.request_id
            if self._migrated:
                payload["_disagg_resumed"] = True
            return (payload,) + self._args[1:], 0
        return self._args, len(self._delivered)

    def _run(self):
        deadline = (None if self._total_timeout_s is None
                    else time.monotonic() + self._total_timeout_s)
        first = (self._args[0]
                 if self._args and isinstance(self._args[0], dict)
                 else {})
        self._router.note_queued(
            self.request_id, prompt_tokens=len(first.get("tokens", ())),
            adapter_id=first.get("adapter_id", ""))
        attempt = 0
        dead: set = set()
        rng = random.Random(self.request_id)
        backoff = 0.05
        while True:
            call_args, skip = self._continuation_args()
            if call_args is None:
                break  # prefix already covers max_new_tokens
            assign_timeout = self._assign_timeout_s
            if deadline is not None:
                left = max(0.0, deadline - time.monotonic())
                assign_timeout = (left if assign_timeout is None
                                  else min(assign_timeout, left))
            gen, replica_id, _ = self._router.assign_streaming(
                self._method_name, call_args, self._kwargs,
                timeout=assign_timeout, exclude=dead,
                model_id=self._model_id, request_id=self.request_id,
                prefer_replica=self._prefer_replica)
            try:
                for ref in gen:
                    item = api.get(ref)
                    if skip > 0:
                        skip -= 1
                        continue
                    self._delivered.append(item)
                    yield item
            except GeneratorExit:
                # Consumer abandoned the stream: release the slot, no
                # retry, no terminal verdict (the request was dropped,
                # not failed).
                self._router.finish_streaming(replica_id)
                raise
            except Exception as err:
                died = _is_death(err)
                self._router.finish_streaming(replica_id, died=died)
                shed = _shed_error(err)
                if shed is not None:
                    # Admission-control shed: terminal immediately —
                    # SHED in the ring (distinct from FAILED: nothing
                    # ran), the unwrapped error to the caller so it can
                    # retry on its own schedule.
                    self._router.note_terminal(
                        self.request_id, _reqev.SHED, cause="ShedError",
                        generated_tokens=len(self._delivered))
                    raise shed from None
                handoff = _migration_handoff(err)
                if handoff is not None and (
                        deadline is None or time.monotonic() < deadline):
                    # Planned prefill→decode handoff, not a failure:
                    # resume immediately (no backoff — the pages are
                    # already waiting on the target) and do not charge
                    # the retry budget.  If the target died in the
                    # meantime, the next attempt's continuation replay
                    # recomputes locally like any other failover.
                    attempt += 1
                    self._migrated = True
                    self._prefer_replica = (handoff.target_replica_id
                                            or None)
                    self._router.note_migrating(
                        self.request_id, attempt, replica_id,
                        handoff.target_replica_id)
                    continue
                budget_left = (
                    _is_retriable(err)
                    and attempt < self._max_retries
                    and (deadline is None or time.monotonic() < deadline))
                if not budget_left:
                    self._router.note_terminal(
                        self.request_id, _reqev.FAILED,
                        cause=type(err).__name__,
                        generated_tokens=len(self._delivered))
                    raise
                if died:
                    dead.add(replica_id)
                attempt += 1
                self._router.note_retry(self.request_id, attempt,
                                        replica_id,
                                        reason=type(err).__name__)
                # Half-fixed + half-jitter (see DeploymentResponse
                # .result): spacing never collapses to ~0, so a bounced
                # request outlasts its replacement replica's startup.
                delay = backoff / 2.0 + rng.uniform(0.0, backoff / 2.0)
                backoff = min(backoff * 2.0, 1.0)
                if deadline is not None:
                    delay = min(delay,
                                max(0.0, deadline - time.monotonic()))
                time.sleep(delay)
                continue
            else:
                self._router.finish_streaming(replica_id)
                break
        self._router.note_terminal(
            self.request_id, _reqev.FINISHED,
            generated_tokens=len(self._delivered))


class DeploymentHandle:
    """Client-side handle to a deployment (one router per process per
    deployment, shared across handle copies)."""

    def __init__(self, deployment_name: str, app_name: str,
                 method_name: str = "__call__",
                 assign_timeout_s: Optional[float] = None,
                 multiplexed_model_id: str = "",
                 stream: bool = False,
                 max_retries: int = 3):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name
        # None = wait for a free replica slot indefinitely (backpressure,
        # the reference's behavior); a number bounds the wait.
        self._assign_timeout_s = assign_timeout_s
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        self._max_retries = max_retries

    def options(self, *, method_name: Optional[str] = None,
                assign_timeout_s: Optional[float] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                max_retries: Optional[int] = None
                ) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name or self._method_name,
            (assign_timeout_s if assign_timeout_s is not None
             else self._assign_timeout_s),
            (multiplexed_model_id if multiplexed_model_id is not None
             else self._multiplexed_model_id),
            (stream if stream is not None else self._stream),
            (max_retries if max_retries is not None
             else self._max_retries),
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # handle.method.remote(...) sugar (parity: handle method access)
        return DeploymentHandle(self.deployment_name, self.app_name, name,
                                self._assign_timeout_s,
                                self._multiplexed_model_id,
                                self._stream, self._max_retries)

    def remote(self, *args, **kwargs):
        args = tuple(self._unwrap(a) for a in args)
        kwargs = {k: self._unwrap(v) for k, v in kwargs.items()}
        router = _router_for(self.app_name, self.deployment_name)
        if self._stream:
            # stream=True handles return a failover-aware generator; the
            # target method (default "stream" when the handle's method
            # was left at __call__) must be @serve-streaming on the
            # replica (LLMServer.stream is).
            method = ("stream" if self._method_name == "__call__"
                      else self._method_name)
            return DeploymentResponseGenerator(
                router, method, args, kwargs,
                assign_timeout_s=self._assign_timeout_s,
                model_id=self._multiplexed_model_id,
                max_retries=self._max_retries,
            )
        method = self._method_name
        timeout = self._assign_timeout_s
        model_id = self._multiplexed_model_id
        dead: set = set()
        last = [None]

        def submit() -> ObjectRef:
            if last[0] is not None:
                dead.add(last[0])
            ref, replica_id = router.assign(
                method, args, kwargs, timeout=timeout, exclude=dead,
                model_id=model_id,
            )
            last[0] = replica_id
            return ref

        return DeploymentResponse(submit(), resubmit=submit)

    @staticmethod
    def _unwrap(value: Any) -> Any:
        # Pass the underlying ref; the actor runtime resolves refs in args
        # before execution (parity: response-to-upstream-arg resolution).
        if isinstance(value, DeploymentResponse):
            return value._to_object_ref()
        return value

    def __repr__(self):
        return (f"DeploymentHandle({self.app_name}/{self.deployment_name}"
                f".{self._method_name})")

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self.deployment_name, self.app_name, self._method_name,
             self._assign_timeout_s, self._multiplexed_model_id,
             self._stream, self._max_retries),
        )
