"""Declarative Serve config: YAML/dict schema → running applications.

Parity with the reference's declarative layer (ray:
python/ray/serve/schema.py — ServeDeploySchema/ServeApplicationSchema;
`serve deploy config.yaml` CLI): a config file names applications by
import path, overrides per-deployment options, and `deploy()` makes the
cluster converge on it.  Re-deploying an edited file updates in place
(the controller reconciles), matching `serve deploy`'s idempotency.

Schema (YAML or JSON):

    http_options:
      port: 8000
      host: 127.0.0.1
    applications:
      - name: app1                      # unique; default "default"
        route_prefix: /app1             # null → no HTTP route
        import_path: my_module:app      # module:attr of a BOUND app
                                        # (or a Deployment — bound with
                                        # no args)
        args: {}                        # kwargs for a builder function
        deployments:                    # per-deployment overrides
          - name: Doubler
            num_replicas: 3
            max_ongoing_requests: 8
            user_config: {threshold: 0.5}
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional

from ray_tpu.serve.deployment import Application, Deployment


@dataclasses.dataclass
class DeploymentOverride:
    name: str
    options: Dict[str, Any]


@dataclasses.dataclass
class ApplicationSpec:
    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = "/"
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    deployments: List[DeploymentOverride] = dataclasses.field(
        default_factory=list
    )


@dataclasses.dataclass
class ServeDeploySchema:
    applications: List[ApplicationSpec]
    http_port: Optional[int] = None
    http_host: str = "127.0.0.1"

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "ServeDeploySchema":
        if not isinstance(raw, dict):
            raise ValueError("serve config must be a mapping")
        apps_raw = raw.get("applications")
        if not isinstance(apps_raw, list) or not apps_raw:
            raise ValueError("config needs a non-empty 'applications' list")
        apps = []
        seen = set()
        for a in apps_raw:
            if "import_path" not in a:
                raise ValueError(f"application missing import_path: {a}")
            overrides = [
                DeploymentOverride(
                    name=d["name"],
                    options={k: v for k, v in d.items() if k != "name"},
                )
                for d in a.get("deployments", [])
            ]
            spec = ApplicationSpec(
                import_path=a["import_path"],
                name=a.get("name", "default"),
                route_prefix=a.get("route_prefix", "/"),
                args=a.get("args") or {},
                deployments=overrides,
            )
            if spec.name in seen:
                raise ValueError(f"duplicate application name {spec.name!r}")
            seen.add(spec.name)
            apps.append(spec)
        http = raw.get("http_options") or {}
        return cls(
            applications=apps,
            http_port=http.get("port"),
            http_host=http.get("host", "127.0.0.1"),
        )

    @classmethod
    def from_file(cls, path: str) -> "ServeDeploySchema":
        with open(path) as f:
            text = f.read()
        try:
            import yaml

            raw = yaml.safe_load(text)
        except ImportError:  # pragma: no cover — pyyaml is baked in
            import json

            raw = json.loads(text)
        return cls.parse(raw)


def _import_attr(path: str):
    if ":" not in path:
        raise ValueError(
            f"import_path must be 'module:attr', got {path!r}"
        )
    mod_name, attr = path.split(":", 1)
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def _build_app(spec: ApplicationSpec) -> Application:
    target = _import_attr(spec.import_path)
    if callable(target) and not isinstance(target, (Application, Deployment)):
        # Builder function: app = build(**args) (parity: app builders
        # taking typed args in the reference schema).
        target = target(**spec.args)
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise ValueError(
            f"{spec.import_path!r} resolved to {type(target).__name__}, "
            f"expected a bound Application (or Deployment/builder)"
        )
    # Apply per-deployment overrides across the graph.
    if spec.deployments:
        by_name = {d.name: d.options for d in spec.deployments}
        target = _apply_overrides(target, by_name, seen=set())
    return target


def _apply_overrides(app: Application, by_name: Dict[str, Dict[str, Any]],
                     seen: set) -> Application:
    """Rebuild the graph with options() applied wherever a deployment
    name matches (nested Applications in init args included)."""
    if id(app) in seen:
        return app
    seen.add(id(app))
    dep = app.deployment
    opts = by_name.get(dep.name)
    if opts:
        dep = dep.options(**opts)

    def walk(v):
        return (_apply_overrides(v, by_name, seen)
                if isinstance(v, Application) else v)

    new_args = tuple(walk(a) for a in app.init_args)
    new_kwargs = {k: walk(v) for k, v in app.init_kwargs.items()}
    return Application(dep, new_args, new_kwargs)


def deploy(config, *, wait_for_ready: bool = True) -> List[str]:
    """Apply a config (path, dict, or schema): start serve if needed,
    run every application.  Returns the deployed app names (parity:
    `serve deploy` → PUT /api/serve/applications)."""
    from ray_tpu import serve

    if isinstance(config, str):
        schema = ServeDeploySchema.from_file(config)
    elif isinstance(config, dict):
        schema = ServeDeploySchema.parse(config)
    else:
        schema = config
    serve.start(http_port=schema.http_port, http_host=schema.http_host)
    names = []
    for spec in schema.applications:
        app = _build_app(spec)
        serve.run(app, name=spec.name, route_prefix=spec.route_prefix,
                  wait_for_ready=wait_for_ready)
        names.append(spec.name)
    return names
