"""Long-poll config push: controller -> routers/proxies.

Parity with the reference (ray: python/ray/serve/_private/long_poll.py —
LongPollHost:172, LongPollClient:63): the host keeps a monotonically
increasing snapshot id per key; clients block in ``listen`` with the ids
they have seen, and are woken with only the keys that changed.  This is
how routing tables reach every handle without polling.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Tuple

# Sentinel returned when a listen times out with no changes.
LISTEN_TIMEOUT = "__listen_timeout__"


class LongPollHost:
    """Lives inside the Serve controller actor."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._snapshots: Dict[str, Tuple[int, Any]] = {}
        self._next_id = 1

    def notify_changed(self, key: str, value: Any) -> None:
        with self._cv:
            self._snapshots[key] = (self._next_id, value)
            self._next_id += 1
            self._cv.notify_all()

    def drop_key(self, key: str) -> None:
        with self._cv:
            self._snapshots.pop(key, None)

    def listen(self, keys_to_ids: Dict[str, int],
               timeout: float = 30.0) -> Dict[str, Tuple[int, Any]]:
        """Block until any subscribed key's snapshot id advances past the
        caller's; return {key: (new_id, value)} for the changed keys."""

        def changed() -> Dict[str, Tuple[int, Any]]:
            out = {}
            for key, seen in keys_to_ids.items():
                snap = self._snapshots.get(key)
                if snap is not None and snap[0] > seen:
                    out[key] = snap
            return out

        with self._cv:
            updates = changed()
            if updates:
                return updates
            self._cv.wait(timeout)
            return changed()


class LongPollClient:
    """Background listener attached to a router/proxy.

    ``callbacks`` maps key -> fn(value); each is invoked with the initial
    snapshot (if any) and then on every change.
    """

    def __init__(self, listen_fn: Callable[[Dict[str, int]], Dict],
                 callbacks: Dict[str, Callable[[Any], None]]):
        self._listen_fn = listen_fn
        self._callbacks = dict(callbacks)
        self._seen: Dict[str, int] = {k: 0 for k in callbacks}
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="long-poll-client"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                updates = self._listen_fn(dict(self._seen))
            except Exception:
                if self._stopped.is_set():
                    return
                self._stopped.wait(0.1)
                continue
            if not updates:
                self._stopped.wait(0.02)  # poll cadence
                continue
            for key, (snap_id, value) in updates.items():
                self._seen[key] = snap_id
                cb = self._callbacks.get(key)
                if cb is not None and not self._stopped.is_set():
                    try:
                        cb(value)
                    except Exception:
                        pass
