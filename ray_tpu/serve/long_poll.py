"""Long-poll config push: controller -> routers/proxies.

Parity with the reference (ray: python/ray/serve/_private/long_poll.py —
LongPollHost:172, LongPollClient:63): the host keeps a monotonically
increasing snapshot id per key; clients block in ``listen`` with the ids
they have seen, and are woken with only the keys that changed.  This is
how routing tables reach every handle without polling.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Tuple

# Sentinel returned when a listen times out with no changes.
LISTEN_TIMEOUT = "__listen_timeout__"

# Client reconnect backoff through controller outages: capped
# exponential, so a dead controller costs ~a poll tick at first and at
# most BACKOFF_MAX_S per retry while the outage lasts.
BACKOFF_MIN_S = 0.05
BACKOFF_MAX_S = 2.0


class LongPollHost:
    """Lives inside the Serve controller actor."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._snapshots: Dict[str, Tuple[int, Any]] = {}
        self._next_id = 1

    def notify_changed(self, key: str, value: Any) -> None:
        with self._cv:
            self._snapshots[key] = (self._next_id, value)
            self._next_id += 1
            self._cv.notify_all()

    def drop_key(self, key: str) -> None:
        with self._cv:
            self._snapshots.pop(key, None)

    def listen(self, keys_to_ids: Dict[str, int],
               timeout: float = 30.0) -> Dict[str, Tuple[int, Any]]:
        """Block until any subscribed key's snapshot id advances past the
        caller's; return {key: (new_id, value)} for the changed keys."""

        def changed() -> Dict[str, Tuple[int, Any]]:
            out = {}
            for key, seen in keys_to_ids.items():
                snap = self._snapshots.get(key)
                if snap is not None and snap[0] > seen:
                    out[key] = snap
            return out

        with self._cv:
            updates = changed()
            if updates:
                return updates
            self._cv.wait(timeout)
            return changed()


class LongPollClient:
    """Background listener attached to a router/proxy.

    ``callbacks`` maps key -> fn(value); each is invoked with the initial
    snapshot (if any) and then on every change.

    Survives controller outages: a failing listen retries with
    capped-exponential backoff, and ``resubscribe`` (when given) is
    called on each failure to build a FRESH listen_fn — re-resolving
    ``CONTROLLER_NAME`` so a replacement controller actor's handle is
    picked up.  Responses of the shape ``{"epoch": E, "updates": {...}}``
    carry the controller epoch: when it moves, the new host's snapshot
    ids restarted from 1 while our ``seen`` values are from the dead
    generation — the client full-resyncs (seen -> 0) so the rebuilt
    tables arrive instead of being filtered forever.
    """

    def __init__(self, listen_fn: Callable[[Dict[str, int]], Dict],
                 callbacks: Dict[str, Callable[[Any], None]],
                 resubscribe: Callable[[], Callable] = None):
        self._listen_fn = listen_fn
        self._callbacks = dict(callbacks)
        self._seen: Dict[str, int] = {k: 0 for k in callbacks}
        self._resubscribe = resubscribe
        self._epoch = None
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="long-poll-client"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        backoff = BACKOFF_MIN_S
        while not self._stopped.is_set():
            try:
                resp = self._listen_fn(dict(self._seen))
            except Exception:
                if self._stopped.is_set():
                    return
                self._stopped.wait(backoff)
                backoff = min(backoff * 2.0, BACKOFF_MAX_S)
                if self._resubscribe is not None:
                    try:
                        self._listen_fn = self._resubscribe()
                    except Exception:
                        pass  # controller still down — keep backing off
                continue
            backoff = BACKOFF_MIN_S
            updates = resp
            if isinstance(resp, dict) and "epoch" in resp \
                    and "updates" in resp:
                epoch, updates = resp["epoch"], resp["updates"]
                if self._epoch is None:
                    self._epoch = epoch
                elif epoch != self._epoch:
                    # Controller restarted: full resync.  Drop this
                    # response's (seen-filtered, possibly empty) updates
                    # and re-listen from zero — the next reply carries
                    # the new generation's complete snapshots.
                    self._epoch = epoch
                    self._seen = {k: 0 for k in self._callbacks}
                    continue
            if not updates:
                self._stopped.wait(0.02)  # poll cadence
                continue
            for key, (snap_id, value) in updates.items():
                self._seen[key] = snap_id
                cb = self._callbacks.get(key)
                if cb is not None and not self._stopped.is_set():
                    try:
                        cb(value)
                    except Exception:
                        pass
