"""ray_tpu.serve — model serving on the actor runtime.

Parity with the reference (ray: python/ray/serve/api.py — serve.run:479,
serve.start, serve.shutdown, @serve.deployment, @serve.batch,
get_deployment_handle/get_app_handle).  TPU-specific addition: the
continuous-batching LLM engine (ray_tpu.serve.llm_engine) — the
reference delegates model inference entirely to user code.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ray_tpu.core import api as _api
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.deployment import (
    Application,
    Deployment,
    build_application,
    deployment,
)
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,
    _shutdown_routers,
)
from ray_tpu.serve.graph import (
    DAGDriver,
    InputNode,
    build_graph_app,
)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "Application", "AutoscalingConfig", "Deployment", "DeploymentConfig",
    "DAGDriver", "DeploymentHandle", "DeploymentResponse",
    "InputNode", "batch", "build_graph_app", "deployment",
    "delete", "get_app_handle", "get_deployment_handle",
    "get_multiplexed_model_id", "multiplexed", "run", "shutdown",
    "start", "status",
]

_proxy = None


def _get_or_create_controller():
    from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController

    if not _api.is_initialized():
        _api.init(ignore_reinit_error=True)
    cls = _api.remote(ServeController)
    # Crash-recoverable control plane: max_restarts covers in-place
    # actor restarts, and a controller that died outright (hard kill,
    # restarts exhausted) is recreated HERE as a fresh actor — either
    # way __init__ reloads the persisted checkpoint, re-censuses the
    # fleet and rebroadcasts before serving, so callers of this
    # function always get a controller that reflects reality.
    return cls.options(
        name=CONTROLLER_NAME, get_if_exists=True, lifetime="detached",
        num_cpus=0, max_concurrency=32, max_restarts=3,
    ).remote()


def start(http_port: Optional[int] = None, http_host: str = "127.0.0.1"):
    """Start the Serve control plane (and optionally the HTTP proxy).
    Parity: serve.start (ray serve/api.py)."""
    global _proxy
    _get_or_create_controller()
    if http_port is not None and _proxy is None:
        from ray_tpu.serve.http import AsyncHTTPProxy

        _proxy = AsyncHTTPProxy(http_host, http_port)
    return _proxy


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", wait_for_ready: bool = True,
        timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment
    (parity: ray serve.run api.py:479)."""
    controller = _get_or_create_controller()
    infos = build_application(app, name)
    _api.get(controller.deploy_application.remote(name, infos, route_prefix))
    if wait_for_ready:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            st = _api.get(controller.status.remote())
            deps = st["applications"].get(name, {}).get("deployments", {})
            if deps and all(
                d["status"] == "HEALTHY" for d in deps.values()
            ):
                break
            time.sleep(0.02)
        else:
            raise TimeoutError(
                f"application {name!r} not healthy after {timeout_s}s: "
                f"{_api.get(controller.status.remote())}"
            )
    ingress = _api.get(controller.get_ingress.remote(name))
    return DeploymentHandle(ingress, name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_or_create_controller()
    ingress = _api.get(controller.get_ingress.remote(name))
    return DeploymentHandle(ingress, name)


def get_deployment_handle(deployment_name: str, app_name: str = "default"
                          ) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def status() -> Dict[str, Any]:
    controller = _get_or_create_controller()
    return _api.get(controller.status.remote())


def delete(name: str, *, wait: bool = True, timeout_s: float = 10.0) -> None:
    controller = _get_or_create_controller()
    _api.get(controller.delete_application.remote(name))
    if wait:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            st = _api.get(controller.status.remote())
            if name not in st["applications"] or not st["applications"][
                name
            ]["deployments"]:
                return
            time.sleep(0.02)


def shutdown(timeout_s: float = 10.0) -> None:
    """Tear down all applications, replicas, proxy and the controller
    (parity: serve.shutdown)."""
    global _proxy
    from ray_tpu.serve.controller import CONTROLLER_NAME

    if _proxy is not None:
        _proxy.shutdown()
        _proxy = None
    _shutdown_routers()
    if not _api.is_initialized():
        return
    try:
        controller = _api.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    try:
        _api.get(controller.graceful_shutdown.remote())
        _api.get(controller.wait_for_drained.remote(timeout_s))
    finally:
        try:
            _api.get(controller.stop_reconcile.remote(), timeout=5.0)
        except Exception:
            pass
        _api.kill(controller, no_restart=True)
