"""Request-lifecycle event ring for the serving plane.

Mirrors the task-event design in ``core/events.py`` one level up the
stack: where the task ring answers "what did this *task* do", this ring
answers "why was this *request* slow" — the one axis the reference's
state API (tasks/actors/objects, SURVEY §2.2) does not cover and an
LLM serving stack cannot live without.  Every ``LLMEngine`` owns a
bounded ring recording each request's state machine

    QUEUED → PREFILLING → DECODING → FINISHED | FAILED | CANCELLED
                                   | PREEMPTED (drained attempt)
    SHED (refused at admission: queue age over the SLO budget)

with wall-clock timestamps, token counts, slot/page assignment and the
terminal cause.  Serve routers keep their own ring per deployment with
the router-side view — QUEUED → RETRYING (per failed attempt, with an
attempt counter + history) → FINISHED | FAILED.  ``util/state.list_requests`` / ``summarize_requests``,
the dashboard's ``/api/v0/requests`` routes, ``raytpu list requests``
and the request rows in ``ray_tpu.timeline()`` all read from here.

Rings register into a process-local weak registry (one entry per live
engine); engines inside worker processes piggyback their rows on task
replies (see ``worker_main._run_op``) exactly like metric snapshots, so
the driver's state API sees every process's requests under a ``proc``
key — absolute last-write-wins snapshots, same federation contract as
``util/metrics.merge_remote``.

The request id is minted once at the serve router and rides request
metadata → a context variable (set by the replica) → ``LLMEngine.submit``
so spans, log lines and this ring all agree on the name of a request.
"""

from __future__ import annotations

import collections
import contextvars
import dataclasses
import threading
import time
import uuid
import weakref
from typing import Any, Dict, List, Optional

# Request state vocabulary (the serving analogue of common.proto's
# TaskStatus in core/events.py).  RETRYING is a router-side state: the
# request's current attempt died (replica preempted or killed) and a
# new attempt is being enqueued on a surviving replica.  PREEMPTED is
# the engine-side terminal for a drained request — the *attempt* ended
# there, the request itself continues elsewhere, so it is deliberately
# distinct from FAILED.
QUEUED = "QUEUED"
PREFILLING = "PREFILLING"
DECODING = "DECODING"
RETRYING = "RETRYING"
# MIGRATING is the disaggregated-serving sibling of RETRYING (also
# router-side, also non-terminal): the prefill attempt finished, its KV
# pages landed on a decode replica, and the stream is being resumed
# there (serve/kv_transfer MigrationHandoff) — a planned handoff, not a
# failure.
MIGRATING = "MIGRATING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
PREEMPTED = "PREEMPTED"
# SHED is the admission-control terminal: the engine refused to queue
# the request because its admission queue was already older than the
# SLO budget (EngineConfig.shed_queue_age_s).  Deliberately distinct
# from FAILED — no attempt ever ran, no work was lost, and the caller
# saw an immediate clean backpressure error instead of a timeout.
SHED = "SHED"

TERMINAL_STATES = (FINISHED, FAILED, CANCELLED, PREEMPTED, SHED)

# Phase labels for the timeline rows: the span covering [state, next
# state) is named after what the engine was doing IN that state.
_PHASE_NAME = {QUEUED: "queued", PREFILLING: "prefill", DECODING: "decode",
               RETRYING: "retrying", MIGRATING: "migrating"}


@dataclasses.dataclass
class RequestRecord:
    """One request's lifecycle (the serving analogue of TaskAttempt)."""

    request_id: str
    engine: str
    state_ts: Dict[str, float] = dataclasses.field(default_factory=dict)
    prompt_tokens: int = 0
    generated_tokens: int = 0
    # Slot/page assignment: None until admitted; num_pages stays None on
    # the non-paged (slot-cache) engine — absent, not zero.
    slot: Optional[int] = None
    num_pages: Optional[int] = None
    terminal_cause: Optional[str] = None
    # Failover bookkeeping (router rings): attempt is the current
    # 0-based attempt number; attempts accumulates one row per retry
    # with the replica it left and why — the "attempt history" shown by
    # ``raytpu list requests --detail``.
    attempt: int = 0
    attempts: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # Prompt tokens served from the engine's prefix cache at admission
    # (0 = cold prefill, or the cache is off) — joins with ttft_s for
    # TTFT-by-hit-depth.
    prefix_hit: int = 0
    # LoRA adapter the request decodes under ("" = base model) — the
    # multi-tenant attribution key for `raytpu list requests`.
    adapter_id: str = ""
    # Speculative decoding: draft tokens proposed / accepted for this
    # request across its verify rounds (both 0 = the request never
    # speculated — temperature > 0, adapter traffic, or spec off).
    spec_drafted: int = 0
    spec_accepted: int = 0

    @property
    def state(self) -> str:
        """Latest state reached (insertion order = record order)."""
        return next(reversed(self.state_ts)) if self.state_ts else "NIL"

    def is_terminal(self) -> bool:
        return any(s in self.state_ts for s in TERMINAL_STATES)

    # -- derived token-latency views (wall clock, from the state stamps)

    @property
    def ttft_s(self) -> Optional[float]:
        """Per-ATTEMPT time to first token.  A resumed stream's survivor
        row lacks the original admission stamp, so the cross-attempt
        truth (TTFT measured from FIRST admission) lives in
        ``stitch_request`` — this property stays the single-ring view."""
        if QUEUED in self.state_ts and DECODING in self.state_ts:
            return self.state_ts[DECODING] - self.state_ts[QUEUED]
        return None

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean per-token latency after the first token (terminal only)."""
        end = next((self.state_ts[s] for s in TERMINAL_STATES
                    if s in self.state_ts), None)
        if (end is None or DECODING not in self.state_ts
                or self.generated_tokens < 2):
            return None
        return (end - self.state_ts[DECODING]) / (self.generated_tokens - 1)

    @property
    def e2e_s(self) -> Optional[float]:
        end = next((self.state_ts[s] for s in TERMINAL_STATES
                    if s in self.state_ts), None)
        if end is None or QUEUED not in self.state_ts:
            return None
        return end - self.state_ts[QUEUED]

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["state"] = self.state
        d["ttft_s"] = self.ttft_s
        d["tpot_s"] = self.tpot_s
        d["e2e_s"] = self.e2e_s
        # Display form for `raytpu list requests`: accepted/drafted,
        # blank when the request never speculated (absent, not "0/0").
        d["spec"] = (f"{self.spec_accepted}/{self.spec_drafted}"
                     if self.spec_drafted else "")
        return d


class RequestEventBuffer:
    """Bounded per-engine ring; oldest *terminal* records are dropped
    first when over capacity (same eviction rule as TaskEventBuffer —
    live requests are the ones an operator is debugging)."""

    def __init__(self, engine: str, max_requests: int = 4096):
        self.engine = engine
        self._lock = threading.Lock()
        self._max = max_requests
        self._records: "collections.OrderedDict[str, RequestRecord]" = \
            collections.OrderedDict()
        self.num_dropped = 0

    def record(self, request_id: str, state: str, *,
               prompt_tokens: Optional[int] = None,
               generated_tokens: Optional[int] = None,
               slot: Optional[int] = None,
               num_pages: Optional[int] = None,
               terminal_cause: Optional[str] = None,
               attempt: Optional[int] = None,
               attempt_info: Optional[Dict[str, Any]] = None,
               prefix_hit: Optional[int] = None,
               adapter_id: Optional[str] = None,
               spec_drafted: Optional[int] = None,
               spec_accepted: Optional[int] = None) -> None:
        now = time.time()
        with self._lock:
            rec = self._records.get(request_id)
            if rec is None:
                rec = RequestRecord(request_id=request_id,
                                    engine=self.engine)
                self._records[request_id] = rec
                if len(self._records) > self._max:
                    self._evict_locked()
            if state in TERMINAL_STATES and rec.is_terminal():
                return  # first terminal verdict wins
            # First-entry wins: a state is ENTERED once; re-records (the
            # incremental-prefill path re-announces PREFILLING at its
            # final chunk, the failover path re-announces RETRYING per
            # attempt) keep the original stamp, so phase timestamps
            # stay monotone in record order.  Retry history rides the
            # attempt counter + attempts log instead of state_ts.
            rec.state_ts.setdefault(state, now)
            if attempt is not None:
                rec.attempt = attempt
            if attempt_info is not None:
                rec.attempts.append(dict(attempt_info, ts=now))
            if prompt_tokens is not None:
                rec.prompt_tokens = prompt_tokens
            if generated_tokens is not None:
                rec.generated_tokens = generated_tokens
            if slot is not None:
                rec.slot = slot
            if num_pages is not None:
                rec.num_pages = num_pages
            if terminal_cause is not None:
                rec.terminal_cause = terminal_cause
            if prefix_hit is not None:
                rec.prefix_hit = prefix_hit
            if adapter_id is not None:
                rec.adapter_id = adapter_id
            if spec_drafted is not None:
                rec.spec_drafted = spec_drafted
            if spec_accepted is not None:
                rec.spec_accepted = spec_accepted
        _flightrec_event(engine=self.engine, request_id=request_id,
                         state=state, attempt=attempt,
                         terminal_cause=terminal_cause)

    def update(self, request_id: str, *,
               generated_tokens: Optional[int] = None,
               spec_drafted: Optional[int] = None,
               spec_accepted: Optional[int] = None) -> None:
        """Touch live counters without a state transition (per-token /
        per-verify-round)."""
        with self._lock:
            rec = self._records.get(request_id)
            if rec is None:
                return
            if generated_tokens is not None:
                rec.generated_tokens = generated_tokens
            if spec_drafted is not None:
                rec.spec_drafted = spec_drafted
            if spec_accepted is not None:
                rec.spec_accepted = spec_accepted

    def _evict_locked(self) -> None:
        for key, rec in self._records.items():
            if rec.is_terminal():
                del self._records[key]
                self.num_dropped += 1
                return
        self._records.popitem(last=False)
        self.num_dropped += 1

    def row(self, request_id: str) -> Optional[Dict[str, Any]]:
        """One request's row dict (or None) without snapshotting the
        whole ring — the engine's per-terminal attribution path."""
        with self._lock:
            rec = self._records.get(request_id)
            if rec is None:
                return None
            rec = dataclasses.replace(
                rec, state_ts=dict(rec.state_ts),
                attempts=[dict(a) for a in rec.attempts])
        d = rec.to_dict()
        d["proc"] = "driver"
        return d

    def snapshot(self) -> List[RequestRecord]:
        with self._lock:
            return [dataclasses.replace(r, state_ts=dict(r.state_ts),
                                        attempts=[dict(a)
                                                  for a in r.attempts])
                    for r in self._records.values()]

    def counts_by_state(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for rec in self.snapshot():
            out[rec.state] = out.get(rec.state, 0) + 1
        return out


def _flightrec_event(**fields) -> None:
    """Feed one ring transition into the always-on flight recorder
    (util/flight_recorder).  Guarded: the recorder must never be able
    to take the request plane down with it."""
    try:
        from ray_tpu.util import flight_recorder
        flight_recorder.record("ring", **fields)
    except Exception:
        pass


# -- cross-attempt stitching ------------------------------------------------

def stitch_request(request_id: str,
                   rows: Optional[List[Dict[str, Any]]] = None,
                   ) -> Optional[Dict[str, Any]]:
    """Join every ring row carrying ``request_id`` — router + engine
    rows, across attempts and processes — into one request-level view.

    A resumed stream (RETRYING failover, MIGRATING disagg handoff)
    re-enters DECODING on a survivor whose ring lacks the original
    QUEUED stamp, so any single row's ``ttft_s``/``e2e_s`` measures the
    attempt, not the request.  Here TTFT/e2e are measured from FIRST
    admission: earliest QUEUED → earliest DECODING / latest genuine
    terminal (PREEMPTED is attempt-terminal — the request continued
    elsewhere — so it never ends the stitched timeline)."""
    if rows is None:
        rows = [r for r in snapshot_rows()
                if r.get("request_id") == request_id]
    if not rows:
        return None

    def min_ts(state: str) -> Optional[float]:
        ts = [r["state_ts"][state] for r in rows
              if state in r.get("state_ts", {})]
        return min(ts) if ts else None

    t_admitted = min_ts(QUEUED)
    t_first_token = min_ts(DECODING)
    genuine = (FINISHED, FAILED, CANCELLED, SHED)
    terminals = [(r["state_ts"][s], s) for r in rows for s in genuine
                 if s in r.get("state_ts", {})]
    t_terminal, state = (max(terminals) if terminals else (None, None))
    if state is None:
        # In flight (or only attempt-terminal PREEMPTED rows so far):
        # surface the most recently entered state across rows.
        entered = [(ts, s) for r in rows
                   for s, ts in r.get("state_ts", {}).items()]
        state = max(entered)[1] if entered else "NIL"
    router_rows = [r for r in rows
                   if str(r.get("engine", "")).startswith("router:")]
    # The router row's count is total tokens DELIVERED across attempts;
    # engine rows count per-attempt generation (a replay regenerates).
    gen_pool = router_rows or rows
    return {
        "request_id": request_id,
        "state": state,
        "t_admitted": t_admitted,
        "t_first_token": t_first_token,
        "t_terminal": t_terminal,
        "ttft_s": (t_first_token - t_admitted
                   if t_admitted is not None and t_first_token is not None
                   else None),
        "e2e_s": (t_terminal - t_admitted
                  if t_admitted is not None and t_terminal is not None
                  else None),
        "attempts": max((int(r.get("attempt") or 0) for r in rows),
                        default=0),
        "prompt_tokens": max((int(r.get("prompt_tokens") or 0)
                              for r in rows), default=0),
        "generated_tokens": max((int(r.get("generated_tokens") or 0)
                                 for r in gen_pool), default=0),
        "rows": len(rows),
    }


# -- process-local registry + cross-process federation ----------------------

_registry_lock = threading.Lock()
# engine id → buffer; weak so a ring lives exactly as long as its engine
# (the engine holds the strong ref) and dead engines drop out of listings.
_buffers: "weakref.WeakValueDictionary[str, RequestEventBuffer]" = \
    weakref.WeakValueDictionary()
# proc key → [row dict, ...] — absolute snapshots shipped on task
# replies by worker processes (see util/metrics._remote_snapshots).
_remote_lock = threading.Lock()
_remote_rows: Dict[str, List[Dict[str, Any]]] = {}


def register(buffer: RequestEventBuffer) -> None:
    with _registry_lock:
        _buffers[buffer.engine] = buffer


def buffers() -> List[RequestEventBuffer]:
    with _registry_lock:
        return list(_buffers.values())


def merge_remote(proc: str, rows: List[Dict[str, Any]]) -> None:
    """Store a worker process's request rows (driver-side half of the
    reply piggyback).  Rows are absolute state: last-write-wins."""
    with _remote_lock:
        _remote_rows[proc] = rows


def clear_remote() -> None:
    with _remote_lock:
        _remote_rows.clear()


def clear() -> None:
    """Drop every registered ring and remote snapshot (tests)."""
    with _registry_lock:
        _buffers.clear()
    clear_remote()


def snapshot_rows(local_only: bool = False) -> List[Dict[str, Any]]:
    """Every known request as a plain dict row: local rings first (proc
    "driver"), then federated worker snapshots under their proc key."""
    rows: List[Dict[str, Any]] = []
    for buf in buffers():
        for rec in buf.snapshot():
            d = rec.to_dict()
            d["proc"] = "driver"
            rows.append(d)
    if not local_only:
        with _remote_lock:
            remote = sorted(_remote_rows.items())
        for proc, shipped in remote:
            for d in shipped:
                d = dict(d)
                d["proc"] = proc
                rows.append(d)
    return rows


# -- request-id propagation -------------------------------------------------

_current_request_id: contextvars.ContextVar = contextvars.ContextVar(
    "raytpu_serve_request_id", default="")


def new_request_id() -> str:
    """Mint the id a request carries end to end (router → replica →
    engine → ring/spans/logs)."""
    return f"req-{uuid.uuid4().hex[:16]}"


def set_request_id(request_id: str):
    """Install the current request id; returns a reset token."""
    return _current_request_id.set(request_id)


def reset_request_id(token) -> None:
    _current_request_id.reset(token)


def get_request_id() -> str:
    return _current_request_id.get()


# -- timeline ---------------------------------------------------------------

def chrome_events() -> List[Dict[str, Any]]:
    """Request rows for the merged chrome-trace timeline: one process
    row per engine (``llmreq:<engine>``), one thread row per slot
    (unadmitted requests land on a ``queue`` row), one complete event
    per lifecycle phase.  Mergeable with the task/span/device rows in
    ``util/state.timeline``."""
    out: List[Dict[str, Any]] = []
    seen_rows = set()
    now = time.time()
    for row in snapshot_rows():
        ts_items = list(row.get("state_ts", {}).items())
        if not ts_items:
            continue
        pid = f"llmreq:{row.get('engine', '?')}"
        if pid not in seen_rows:
            seen_rows.add(pid)
            out.append({"ph": "M", "pid": pid, "name": "process_name",
                        "args": {"name": pid}})
        slot = row.get("slot")
        tid = "queue" if slot is None else f"slot {slot}"
        for i, (st, t0) in enumerate(ts_items):
            if st in TERMINAL_STATES:
                continue
            t1 = ts_items[i + 1][1] if i + 1 < len(ts_items) else now
            out.append({
                "ph": "X",
                "name": _PHASE_NAME.get(st, st.lower()),
                "cat": "request",
                "pid": pid,
                "tid": tid,
                "ts": t0 * 1e6,
                "dur": max(0.0, t1 - t0) * 1e6,
                "args": {
                    "request_id": row["request_id"],
                    "state": row.get("state"),
                    "terminal_cause": row.get("terminal_cause"),
                    "generated_tokens": row.get("generated_tokens"),
                },
            })
    return out
