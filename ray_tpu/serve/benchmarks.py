"""Serve microbenchmarks: qps + latency percentiles.

Parity: ray: python/ray/serve/benchmarks/microbenchmark.py (no-op
deployment qps via handle and HTTP, batched throughput) and the
release workloads under release/serve_tests/workloads/ — the numbers
land in BASELINE.md.

Run: ``python -m ray_tpu.serve.benchmarks``
"""

from __future__ import annotations

import json
import time
from typing import Dict, List


def _percentiles(latencies_ms: List[float]) -> Dict[str, float]:
    xs = sorted(latencies_ms)

    def pct(p: float) -> float:
        idx = min(len(xs) - 1, int(p / 100 * len(xs)))
        return xs[idx]

    return {"p50_ms": round(pct(50), 3), "p90_ms": round(pct(90), 3),
            "p99_ms": round(pct(99), 3)}


def bench_handle_noop(num_requests: int = 2000, num_replicas: int = 1,
                      concurrency: int = 32) -> Dict[str, float]:
    """qps + latency of a no-op deployment through DeploymentHandle
    (parity: microbenchmark.py's handle path)."""
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=num_replicas,
                      max_ongoing_requests=concurrency)
    class Noop:
        def __call__(self):
            return b"ok"

    handle = serve.run(Noop.bind(), name=f"bench-noop-{num_replicas}")
    # Warmup.
    for _ in range(50):
        handle.remote().result(timeout_s=30)

    latencies: List[float] = []
    t0 = time.perf_counter()
    inflight = []
    done = 0
    while done < num_requests:
        while len(inflight) < concurrency and \
                done + len(inflight) < num_requests:
            inflight.append((time.perf_counter(), handle.remote()))
        started, resp = inflight.pop(0)
        resp.result(timeout_s=30)
        latencies.append((time.perf_counter() - started) * 1000)
        done += 1
    dt = time.perf_counter() - t0
    out = {"qps": round(num_requests / dt, 1),
           "num_replicas": num_replicas, **_percentiles(latencies)}
    return out


def bench_batching(num_requests: int = 2000,
                   max_batch_size: int = 64) -> Dict[str, float]:
    """Throughput with @serve.batch dynamic batching (parity:
    microbenchmark.py batched path)."""
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=256)
    class Batched:
        @serve.batch(max_batch_size=max_batch_size,
                     batch_wait_timeout_s=0.002)
        def handle_batch(self, items):
            return [x * 2 for x in items]

        def __call__(self, x: int = 1):
            return self.handle_batch(x)

    handle = serve.run(Batched.bind(), name="bench-batched")
    for _ in range(20):
        handle.remote(1).result(timeout_s=30)
    t0 = time.perf_counter()
    resps = [handle.remote(i) for i in range(num_requests)]
    for r in resps:
        r.result(timeout_s=60)
    dt = time.perf_counter() - t0
    return {"qps": round(num_requests / dt, 1),
            "max_batch_size": max_batch_size}


def main() -> None:
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    results = {
        "handle_noop_1_replica": bench_handle_noop(num_replicas=1),
        "handle_noop_4_replicas": bench_handle_noop(num_replicas=4),
        "dynamic_batching": bench_batching(),
    }
    serve.shutdown()
    print(json.dumps(results))


if __name__ == "__main__":
    main()
