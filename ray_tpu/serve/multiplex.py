"""Model multiplexing: many models per deployment, LRU per replica.

Parity: ray: python/ray/serve/multiplex.py (``@serve.multiplexed`` with
``max_num_models_per_replica``, ``serve.get_multiplexed_model_id``,
model-aware routing in _private/replica_scheduler).  A deployment
hosts a loader method decorated ``@multiplexed``; requests carry a
model id (``handle.options(multiplexed_model_id=...)``); the router
keeps model→replica affinity so repeat requests land where the model
is already resident, and each replica LRU-evicts beyond the cap.
"""

from __future__ import annotations

import collections
import contextvars
import functools
import inspect
import threading
from typing import Any, Callable, Optional

_ATTR = "_serve_multiplexed_models"

_current_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """Model id of the in-flight request (parity:
    serve.get_multiplexed_model_id)."""
    return _current_model_id.get()


def _set_model_id(model_id: str):
    return _current_model_id.set(model_id)


def _reset_model_id(token) -> None:
    _current_model_id.reset(token)


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorate a model-loader method ``def get_model(self, model_id)``
    (sync or async).  Calls are LRU-cached per replica instance up to
    ``max_num_models_per_replica``; eviction drops the oldest model
    (its __del__, if any, releases resources — parity with the
    reference's eviction calling the model's destructor)."""

    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def decorate(loader: Callable) -> Callable:
        lock = threading.Lock()

        def _lookup(self, model_id: str):
            with lock:
                cache = getattr(self, _ATTR, None)
                if cache is None:
                    cache = collections.OrderedDict()
                    setattr(self, _ATTR, cache)
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache, cache[model_id], True
                return cache, None, False

        def _admit(cache, model_id: str, model):
            with lock:
                cache[model_id] = model
                cache.move_to_end(model_id)
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)  # LRU eviction

        if inspect.iscoroutinefunction(loader):
            # Async loader → async wrapper, awaitable from async
            # deployments (parity: the reference's multiplexed wrapper
            # is async-native).
            @functools.wraps(loader)
            async def awrapper(self, model_id: str):
                cache, model, hit = _lookup(self, model_id)
                if hit:
                    return model
                model = await loader(self, model_id)
                _admit(cache, model_id, model)
                return model

            awrapper.__serve_multiplexed__ = True
            return awrapper

        @functools.wraps(loader)
        def wrapper(self, model_id: str):
            cache, model, hit = _lookup(self, model_id)
            if hit:
                return model
            model = loader(self, model_id)
            if inspect.iscoroutine(model):
                raise TypeError(
                    "loader returned a coroutine from a sync wrapper — "
                    "declare it `async def` so @multiplexed builds the "
                    "async wrapper"
                )
            _admit(cache, model_id, model)
            return model

        wrapper.__serve_multiplexed__ = True
        return wrapper

    if func is not None:
        return decorate(func)
    return decorate


def loaded_model_ids(instance: Any) -> list:
    """Model ids currently resident on a replica's user instance."""
    cache = getattr(instance, _ATTR, None)
    return list(cache) if cache else []
