"""Model multiplexing: many models per deployment, LRU per replica.

Parity: ray: python/ray/serve/multiplex.py (``@serve.multiplexed`` with
``max_num_models_per_replica``, ``serve.get_multiplexed_model_id``,
model-aware routing in _private/replica_scheduler).  A deployment
hosts a loader method decorated ``@multiplexed``; requests carry a
model id (``handle.options(multiplexed_model_id=...)``); the router
keeps model→replica affinity so repeat requests land where the model
is already resident, and each replica LRU-evicts beyond the cap.
"""

from __future__ import annotations

import collections
import contextvars
import functools
import inspect
import threading
from typing import Any, Callable, Optional

_ATTR = "_serve_multiplexed_models"

_current_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """Model id of the in-flight request (parity:
    serve.get_multiplexed_model_id)."""
    return _current_model_id.get()


def _set_model_id(model_id: str):
    return _current_model_id.set(model_id)


def _reset_model_id(token) -> None:
    _current_model_id.reset(token)


class _MultiplexedCallable:
    """The @multiplexed wrapper as a picklable descriptor: the lock and
    in-flight table are rebuilt fresh on unpickle so deployment classes
    carrying a multiplexed loader ship to replica worker processes
    (a closure capturing a threading.Lock cannot cross the boundary)."""

    __serve_multiplexed__ = True

    def __init__(self, loader: Callable, max_num_models_per_replica: int):
        self._loader = loader
        self._max = max_num_models_per_replica
        self._is_async = inspect.iscoroutinefunction(loader)
        functools.update_wrapper(self, loader)
        self._init_runtime_state()

    def _init_runtime_state(self) -> None:
        self._lock = threading.Lock()
        # (instance id, model id) → Event while a load is in flight:
        # concurrent requests for the same unloaded model wait for ONE
        # load instead of duplicating it (parity: the reference
        # serializes loads per model id).
        self._inflight: dict = {}

    def __reduce__(self):
        return (_MultiplexedCallable, (self._loader, self._max))

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return functools.partial(self.__call__, obj)

    def _try_acquire_load_slot(self, owner, model_id: str):
        """One non-blocking step: (cache, model, 'hit') on cache hit,
        (cache, None, 'load') if this caller is elected to load,
        (cache, event, 'wait') if another load is in flight."""
        key = (id(owner), model_id)
        with self._lock:
            cache = getattr(owner, _ATTR, None)
            if cache is None:
                cache = collections.OrderedDict()
                setattr(owner, _ATTR, cache)
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache, cache[model_id], "hit"
            ev = self._inflight.get(key)
            if ev is None:
                self._inflight[key] = threading.Event()
                return cache, None, "load"
            return cache, ev, "wait"

    def _finish_load(self, owner, cache, model_id: str, model,
                     success: bool) -> None:
        key = (id(owner), model_id)
        with self._lock:
            if success:
                cache[model_id] = model
                cache.move_to_end(model_id)
                while len(cache) > self._max:
                    cache.popitem(last=False)  # LRU eviction
            ev = self._inflight.pop(key, None)
        if ev is not None:
            ev.set()

    async def _acall(self, owner, model_id: str):
        """Async path — awaitable from async deployments (parity: the
        reference's multiplexed wrapper is async-native)."""
        import asyncio

        while True:
            cache, out, state = self._try_acquire_load_slot(
                owner, model_id
            )
            if state == "hit":
                return out
            if state == "load":
                break
            # Another coroutine/thread is loading: yield the loop while
            # waiting (a blocking Event.wait here would deadlock a
            # single-loop pair of requests).
            while not out.is_set():
                await asyncio.sleep(0.005)
        try:
            model = await self._loader(owner, model_id)
        except BaseException:
            self._finish_load(owner, cache, model_id, None, False)
            raise
        self._finish_load(owner, cache, model_id, model, True)
        return model

    def __call__(self, owner, model_id: str):
        if self._is_async:
            return self._acall(owner, model_id)
        while True:
            cache, out, state = self._try_acquire_load_slot(
                owner, model_id
            )
            if state == "hit":
                return out
            if state == "load":
                break
            out.wait()
        try:
            model = self._loader(owner, model_id)
            if inspect.iscoroutine(model):
                raise TypeError(
                    "loader returned a coroutine from a sync wrapper "
                    "— declare it `async def` so @multiplexed builds "
                    "the async wrapper"
                )
        except BaseException:
            self._finish_load(owner, cache, model_id, None, False)
            raise
        self._finish_load(owner, cache, model_id, model, True)
        return model


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorate a model-loader method ``def get_model(self, model_id)``
    (sync or async).  Calls are LRU-cached per replica instance up to
    ``max_num_models_per_replica``; eviction drops the oldest model
    (its __del__, if any, releases resources — parity with the
    reference's eviction calling the model's destructor)."""

    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def decorate(loader: Callable) -> Callable:
        return _MultiplexedCallable(loader, max_num_models_per_replica)

    if func is not None:
        return decorate(func)
    return decorate


def loaded_model_ids(instance: Any) -> list:
    """Model ids currently resident on a replica's user instance."""
    cache = getattr(instance, _ATTR, None)
    return list(cache) if cache else []
