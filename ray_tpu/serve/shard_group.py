"""Shard-group ambient context — how a replica's user callable learns
it is rank 0 of a multi-host tensor-parallel group.

The controller starts one ReplicaActor (rank 0, the streaming
endpoint the router addresses) plus ``size - 1`` ShardMemberActor
processes through a placement group.  Rank 0's ReplicaActor installs a
:class:`ShardGroupContext` BEFORE constructing the user callable;
engine-hosting callables (serve.llm_engine.LLMServer) read it via
:func:`current_shard_group` and build their serving mesh
(parallel.mesh.create_serving_mesh) accordingly — ``dcn_tp`` spanning
the group members, ``tp`` the in-host chips.

On the CPU test backend the hybrid mesh lives over virtual devices
inside rank 0's process (contiguous device groups emulate the host
boundary) while the other members are real actors whose death fails
the whole group; on real multi-host TPU the members each hold a slice
of the same jax.distributed runtime and the mesh spans processes —
the context carries everything both layouts need.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class ShardGroupContext:
    """What one member of a shard group knows about its group."""

    group_id: str            # controller-minted, == replica_id
    rank: int                # this process's rank; 0 hosts the engine
    size: int                # number of member processes
    tensor_parallel: int     # in-host tp ways per member
    dcn_collective: str      # "int8" | "bf16"
    member_ids: List[str] = dataclasses.field(default_factory=list)

    @property
    def quantized(self) -> bool:
        return self.dcn_collective == "int8"


_LOCAL = threading.local()


def set_shard_group(ctx: Optional[ShardGroupContext]) -> None:
    """Install (or clear, with None) the ambient shard-group context.
    Called by ReplicaActor before constructing the user callable, in
    the thread that runs the constructor."""
    _LOCAL.ctx = ctx


def current_shard_group() -> Optional[ShardGroupContext]:
    """The ambient context, or None outside any shard group (plain
    single-process replicas — the common case)."""
    return getattr(_LOCAL, "ctx", None)
