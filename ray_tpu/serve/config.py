"""Serve configuration dataclasses.

Parity with the reference (ray: python/ray/serve/config.py
``AutoscalingConfig``/``DeploymentConfig``; schema objects
python/ray/serve/schema.py).  Kept as plain dataclasses — declarative
YAML can be layered on top by parsing into these.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass(frozen=True)
class AutoscalingConfig:
    """Queue-length-driven autoscaling (parity: ray
    serve/_private/autoscaling_policy.py + serve/config.py
    AutoscalingConfig)."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    # How often replicas push their ongoing-request count to the controller.
    metrics_interval_s: float = 0.2
    # Average the pushed metrics over this trailing window.
    look_back_period_s: float = 2.0
    # A scale decision must hold for this long before it is applied.
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    # SLO-pressure scale-up (None = ongoing-count policy only).  The
    # replicas push their engine's admission-queue age and goodput
    # ratio next to the ongoing count; when the worst reported queue
    # age exceeds target_queue_age_s, or the worst reported goodput
    # drops below target_goodput, the controller forces at least one
    # step up from the current target (and refuses to scale down) even
    # if the averaged ongoing count alone would not.  Queue age is the
    # leading signal — it climbs before latency SLOs blow — and
    # goodput is the trailing guard against scaling down a fleet that
    # is already missing its objectives.
    target_queue_age_s: Optional[float] = None
    target_goodput: Optional[float] = None
    # Predictive scale-up (None = reactive policy only, byte-for-byte
    # unchanged).  Replicas push their engine's cumulative arrival
    # count next to ongoing/queue-age/goodput; the controller keeps an
    # EWMA arrival rate per deployment (serve/signals.ArrivalSignal)
    # and, when the rate's least-squares slope exceeds this many
    # requests/s per second, forces one step up (decision reason
    # "arrival_slope") BEFORE any queue forms — arrival rate leads
    # queue age, which leads latency, so reacting to the slope buys a
    # replica's startup time ahead of SLO pressure.  Veto rules and
    # the DRAINING-only scale-down path are untouched.
    upscale_slope_threshold: Optional[float] = None
    # Arrival-signal shape: EWMA half-life and the trailing window the
    # slope is fit over.
    arrival_half_life_s: float = 2.0
    arrival_slope_window_s: float = 5.0

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < max(1, self.min_replicas):
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}/{self.max_replicas}"
            )
        if self.target_ongoing_requests <= 0:
            raise ValueError("target_ongoing_requests must be positive")
        if (self.target_queue_age_s is not None
                and self.target_queue_age_s <= 0):
            raise ValueError("target_queue_age_s must be positive")
        if (self.target_goodput is not None
                and not 0.0 < self.target_goodput <= 1.0):
            raise ValueError("target_goodput must be in (0, 1]")
        if (self.upscale_slope_threshold is not None
                and self.upscale_slope_threshold <= 0):
            raise ValueError("upscale_slope_threshold must be positive")
        if self.arrival_half_life_s <= 0:
            raise ValueError("arrival_half_life_s must be positive")
        if self.arrival_slope_window_s <= 0:
            raise ValueError("arrival_slope_window_s must be positive")


@dataclasses.dataclass(frozen=True)
class ShardGroupConfig:
    """One replica = ``size`` engine processes forming a single logical
    tensor-parallel shard group (the multi-host serving unit): weights
    shard over ``tensor_parallel`` ways inside each host (ICI) and over
    the ``size`` group members across hosts (DCN).  The controller
    allocates members through one placement group, the router addresses
    the group's rank 0, and ANY member death is whole-replica failure
    (the drain/failover path treats the group as one unit)."""

    size: int = 2
    # In-host tensor-parallel ways per member ("tp" mesh axis).
    tensor_parallel: int = 1
    # DCN leg of the per-layer decode allreduces: "int8" (EQuARX-style
    # quantized, per-chunk scales) or "bf16" (exact-psum fallback).
    dcn_collective: str = "int8"
    # Per-member bundle resources for the group's placement group.
    bundle_resources: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"CPU": 1})
    placement_strategy: str = "PACK"

    def __post_init__(self):
        if self.size < 2:
            raise ValueError("shard_group.size must be >= 2 (a size-1 "
                             "group is just a plain replica)")
        if self.tensor_parallel < 1:
            raise ValueError("shard_group.tensor_parallel must be >= 1")
        if self.dcn_collective not in ("int8", "bf16"):
            raise ValueError(
                f"shard_group.dcn_collective must be 'int8' or 'bf16', "
                f"got {self.dcn_collective!r}")


@dataclasses.dataclass(frozen=True)
class DisaggConfig:
    """Disaggregated prefill/decode serving: the controller assigns
    ``prefill_replicas`` of the deployment's replicas the ``prefill``
    role and the rest ``decode``.  The router sends fresh requests to a
    prefill replica; after ``handoff_after_tokens`` generated tokens the
    prefill replica migrates the request's KV pages to a decode replica
    (serve/kv_transfer) and the stream resumes there.  Any transfer
    failure falls back to the PR-5 continuation replay — local
    recompute, never a stall."""

    # How many replicas get the prefill role (rest are decode).
    prefill_replicas: int = 1
    # Page payload wire format: "int8" (per-page quantized, PR-9 style
    # scales) or "exact" (raw dtype bytes).
    transfer: str = "int8"
    # Tokens the prefill replica generates before handing off (>= 1 so
    # the finished prompt's pages land in the prefix trie first).
    handoff_after_tokens: int = 1
    # Budget for one lease+export+ingest round trip before falling back
    # to local recompute.
    migration_timeout_s: float = 5.0

    def __post_init__(self):
        if self.prefill_replicas < 1:
            raise ValueError("disagg.prefill_replicas must be >= 1")
        if self.transfer not in ("int8", "exact"):
            raise ValueError(
                f"disagg.transfer must be 'int8' or 'exact', "
                f"got {self.transfer!r}")
        if self.handoff_after_tokens < 1:
            raise ValueError("disagg.handoff_after_tokens must be >= 1")
        if self.migration_timeout_s <= 0:
            raise ValueError("disagg.migration_timeout_s must be positive")


@dataclasses.dataclass(frozen=True)
class DeploymentConfig:
    """Per-deployment knobs (parity: ray serve/config.py DeploymentConfig)."""

    num_replicas: int = 1
    max_ongoing_requests: int = 16
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 5.0
    graceful_shutdown_timeout_s: float = 5.0
    # Resources for each replica actor (parity: ray_actor_options).
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Multi-host tensor-parallel replicas (None = plain single-process).
    shard_group: Optional[ShardGroupConfig] = None
    # Disaggregated prefill/decode roles (None = every replica unified).
    disagg: Optional[DisaggConfig] = None

    def __post_init__(self):
        if self.num_replicas < 0:
            raise ValueError("num_replicas must be >= 0")
        if self.max_ongoing_requests < 1:
            raise ValueError("max_ongoing_requests must be >= 1")
        if self.disagg is not None:
            if self.autoscaling_config is not None:
                raise ValueError(
                    "disagg does not compose with autoscaling_config yet "
                    "(role census needs a fixed replica target)")
            if self.num_replicas <= self.disagg.prefill_replicas:
                raise ValueError(
                    f"disagg needs num_replicas > prefill_replicas so at "
                    f"least one decode replica exists, got "
                    f"{self.num_replicas} <= {self.disagg.prefill_replicas}")

    def initial_target_replicas(self) -> int:
        if self.autoscaling_config is not None:
            return max(self.autoscaling_config.min_replicas, 1)
        return self.num_replicas
