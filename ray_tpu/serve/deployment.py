"""@deployment decorator, Deployment, and Application graphs.

Parity with the reference (ray: python/ray/serve/deployment.py
``Deployment``/``Application``; api.py ``@serve.deployment:...``).
``D.bind(*args)`` builds a lazy application graph; args that are
themselves Applications become DeploymentHandles at deploy time
(parity: serve/_private/deployment_graph_build.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.serve.config import (
    AutoscalingConfig,
    DeploymentConfig,
    DisaggConfig,
    ShardGroupConfig,
)


@dataclasses.dataclass(frozen=True)
class Deployment:
    """An un-deployed template: callable + config."""

    func_or_class: Callable
    name: str
    config: DeploymentConfig

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def options(self, **overrides) -> "Deployment":
        """Copy with config overrides, e.g. ``D.options(num_replicas=3)``."""
        name = overrides.pop("name", self.name)
        cfg_fields = {f.name for f in dataclasses.fields(DeploymentConfig)}
        bad = set(overrides) - cfg_fields
        if bad:
            raise ValueError(f"unknown deployment option(s): {sorted(bad)}")
        return Deployment(
            self.func_or_class, name,
            dataclasses.replace(self.config, **overrides),
        )


class Application:
    """A bound deployment graph node (parity: serve Application)."""

    def __init__(self, deployment: Deployment, init_args: tuple,
                 init_kwargs: dict):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs


def deployment(
    _func_or_class: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Optional[int] = None,
    max_ongoing_requests: int = 16,
    user_config: Optional[Any] = None,
    autoscaling_config: Optional[AutoscalingConfig] = None,
    health_check_period_s: float = 1.0,
    graceful_shutdown_timeout_s: float = 5.0,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    shard_group: Optional[Any] = None,
    disagg: Optional[Any] = None,
) -> Any:
    """``@serve.deployment`` (parity: ray serve/api.py deployment:...).

    ``shard_group``: a ShardGroupConfig (or kwargs dict) making each
    replica a multi-host tensor-parallel shard group of engine
    processes instead of one actor (serve/shard_group.py).

    ``disagg``: a DisaggConfig (or kwargs dict) splitting the replica
    set into prefill and decode roles with cross-replica KV page
    migration (serve/kv_transfer.py)."""
    if isinstance(autoscaling_config, dict):
        autoscaling_config = AutoscalingConfig(**autoscaling_config)
    if isinstance(shard_group, dict):
        shard_group = ShardGroupConfig(**shard_group)
    if isinstance(disagg, dict):
        disagg = DisaggConfig(**disagg)
    if num_replicas is not None and autoscaling_config is not None:
        raise ValueError(
            "num_replicas and autoscaling_config are mutually exclusive"
        )

    def wrap(target: Callable) -> Deployment:
        cfg = DeploymentConfig(
            num_replicas=num_replicas if num_replicas is not None else 1,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            autoscaling_config=autoscaling_config,
            health_check_period_s=health_check_period_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            ray_actor_options=dict(ray_actor_options or {}),
            shard_group=shard_group,
            disagg=disagg,
        )
        return Deployment(target, name or target.__name__, cfg)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


@dataclasses.dataclass
class DeploymentInfo:
    """Flattened node of an application graph, ready for the controller."""

    name: str
    func_or_class: Callable
    config: DeploymentConfig
    init_args: tuple
    init_kwargs: dict
    is_ingress: bool = False


def build_application(app: Application, app_name: str) -> List[DeploymentInfo]:
    """Flatten an Application graph into deployment infos.

    Nested Applications in init args/kwargs are replaced with
    ``_HandlePlaceholder``s, resolved into live DeploymentHandles inside
    each replica (parity: serve/_private/deployment_graph_build.py).
    """
    infos: Dict[int, DeploymentInfo] = {}
    names_seen: Dict[str, int] = {}

    def visit(node: Application) -> "_HandlePlaceholder":
        key = id(node)
        if key not in infos:
            name = node.deployment.name
            if name in names_seen and names_seen[name] != key:
                raise ValueError(
                    f"duplicate deployment name {name!r} in application "
                    f"{app_name!r} — use .options(name=...) to disambiguate"
                )
            names_seen[name] = key
            # Reserve the slot first so diamond graphs terminate.
            infos[key] = None  # type: ignore[assignment]
            args = tuple(_replace(a, visit) for a in node.init_args)
            kwargs = {k: _replace(v, visit) for k, v in node.init_kwargs.items()}
            infos[key] = DeploymentInfo(
                name=name,
                func_or_class=node.deployment.func_or_class,
                config=node.deployment.config,
                init_args=args,
                init_kwargs=kwargs,
            )
        return _HandlePlaceholder(node.deployment.name, app_name)

    visit(app)
    out = [i for i in infos.values() if i is not None]
    out[0].is_ingress = True
    return out


def _replace(value: Any, visit: Callable) -> Any:
    if isinstance(value, Application):
        return visit(value)
    if isinstance(value, (list, tuple)):
        t = type(value)
        return t(_replace(v, visit) for v in value)
    if isinstance(value, dict):
        return {k: _replace(v, visit) for k, v in value.items()}
    return value


@dataclasses.dataclass(frozen=True)
class _HandlePlaceholder:
    """Marker swapped for a DeploymentHandle when the replica constructs
    its user callable."""

    deployment_name: str
    app_name: str
