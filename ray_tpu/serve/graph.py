"""Serve deployment graphs — DAG → multi-deployment application.

Parity with the reference's deployment-graph build
(ray: python/ray/serve/_private/deployment_graph_build.py and the
DAGDriver ingress): a request-time dataflow is authored with the DAG
idiom —

    with serve.InputNode() as inp:
        a = Preprocess.bind()           # @serve.deployment class
        b = Model.bind()
        out = b.predict.bind(a.clean.bind(inp))
    app = serve.build_graph_app(out)
    serve.run(app)

Each bound deployment becomes its OWN deployment with independent
replica scaling; ``build_graph_app`` flattens the method-call DAG into
a declarative node spec and wraps it in a generated ingress deployment
(the DAGDriver) that executes the spec per request, passing
DeploymentResponses straight into downstream handles so independent
branches run pipelined, never serialized through ``.result()``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.serve.deployment import Application, Deployment, deployment


class InputNode:
    """Placeholder for the per-request input (parity:
    ray.dag.InputNode used by serve graphs)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class DAGMethodNode:
    """One ``app.method.bind(...)`` call in the request dataflow."""

    def __init__(self, app: Application, method: str, args: tuple,
                 kwargs: dict):
        self.app = app
        self.method = method
        self.args = args
        self.kwargs = kwargs

    def __getattr__(self, name: str):
        raise AttributeError(
            f"DAGMethodNode has no attribute {name!r} — chain further "
            f"calls on a bound deployment, not on a method node")


class _MethodBinder:
    def __init__(self, app: Application, method: str):
        self._app = app
        self._method = method

    def bind(self, *args, **kwargs) -> DAGMethodNode:
        return DAGMethodNode(self._app, self._method, args, kwargs)


def _app_getattr(self: Application, name: str):
    if name.startswith("_"):
        raise AttributeError(name)
    target = self.deployment.func_or_class
    # Only real methods of the deployment's class bind — a typo'd
    # attribute must stay a loud AttributeError, not become a silent
    # _MethodBinder.
    if not hasattr(target, name):
        raise AttributeError(
            f"Application has no attribute {name!r} and deployment "
            f"class {getattr(target, '__name__', target)!r} defines "
            f"no such method")
    return _MethodBinder(self, name)


# Application grows the method-binding surface here (kept out of
# deployment.py so the graph layer owns the DAG idiom).
Application.__getattr__ = _app_getattr  # type: ignore[attr-defined]


# --- declarative node spec (what ships into the driver) --------------------
#
# Arg references: ("input",) | ("node", idx) | ("const", value).

@dataclasses.dataclass
class _NodeSpec:
    deployment_name: str
    method: str
    args: Tuple[Any, ...]
    kwargs: Dict[str, Any]


class DAGDriver:
    """Generated ingress: executes the node spec per request.

    Submits each node as soon as its argument nodes are SUBMITTED
    (DeploymentResponses pass straight into downstream ``.remote``
    calls — the composition contract), so parallel branches pipeline;
    only the terminal node's response is resolved."""

    def __init__(self, spec: List[_NodeSpec], handles: Dict[str, Any]):
        self._spec = spec
        self._handles = handles

    def __call__(self, request_value: Any) -> Any:
        results: List[Any] = []
        for node in self._spec:
            def deref(ref, nested=False):
                kind = ref[0]
                if kind == "input":
                    return request_value
                if kind == "node":
                    r = results[ref[1]]
                    # Replicas resolve upstream responses only at the
                    # TOP level of the args tuple; a response nested
                    # inside a container must resolve here (that
                    # branch loses pipelining — keep hot-path nodes as
                    # direct arguments).
                    return r.result() if nested else r
                if kind == "seq":
                    seq = [deref(e, nested=True) for e in ref[2]]
                    return tuple(seq) if ref[1] else seq
                if kind == "map":
                    return {k: deref(e, nested=True)
                            for k, e in ref[1].items()}
                return ref[1]  # const

            handle = self._handles[node.deployment_name]
            method = getattr(handle, node.method)
            resp = method.remote(*[deref(a) for a in node.args],
                                 **{k: deref(v)
                                    for k, v in node.kwargs.items()})
            results.append(resp)
        return results[-1].result()


def build_graph_app(output: DAGMethodNode, *,
                    driver_name: str = "DAGDriver",
                    max_ongoing_requests: int = 64) -> Application:
    """Flatten a method-call DAG into one Application: the returned
    ingress wraps a DAGDriver whose init args carry each bound
    deployment as a nested Application — the existing
    ``build_application`` pass turns those into DeploymentHandles, so
    every graph node scales independently."""
    if not isinstance(output, DAGMethodNode):
        raise TypeError("build_graph_app expects the DAG's terminal "
                        "app.method.bind(...) node")
    order: List[DAGMethodNode] = []
    index: Dict[int, int] = {}
    apps: Dict[str, Application] = {}
    visiting: set = set()

    def visit(node: DAGMethodNode) -> int:
        key = id(node)
        if key in index:
            return index[key]
        if key in visiting:
            raise ValueError("deployment graph has a cycle")
        visiting.add(key)
        name = node.app.deployment.name
        seen = apps.get(name)
        if seen is not None and seen is not node.app:
            raise ValueError(
                f"duplicate deployment name {name!r} in the graph — "
                f"use .options(name=...) to disambiguate")
        apps[name] = node.app

        def ref_of(v) -> Tuple:
            if isinstance(v, InputNode):
                return ("input",)
            if isinstance(v, DAGMethodNode):
                return ("node", visit(v))
            if isinstance(v, Application):
                raise TypeError(
                    "a bound deployment appeared as a call argument — "
                    "bind a METHOD of it (app.method.bind(...)) or "
                    "pass it as an init arg instead")
            # Containers recurse so nodes nested in lists/dicts wire
            # up instead of shipping as opaque constants.
            if isinstance(v, (list, tuple)):
                return ("seq", type(v) is tuple,
                        tuple(ref_of(e) for e in v))
            if isinstance(v, dict):
                return ("map", {k: ref_of(e) for k, e in v.items()})
            return ("const", v)

        spec_args = tuple(ref_of(a) for a in node.args)
        spec_kwargs = {k: ref_of(v) for k, v in node.kwargs.items()}
        visiting.discard(key)
        order.append(node)
        idx = len(order) - 1
        index[key] = idx
        node._spec = _NodeSpec(name, node.method, spec_args,
                               spec_kwargs)  # type: ignore[attr-defined]
        return idx

    visit(output)
    spec = [n._spec for n in order]  # type: ignore[attr-defined]
    driver = deployment(
        DAGDriver, name=driver_name,
        max_ongoing_requests=max_ongoing_requests)
    # Nested Applications in init args become DeploymentHandles at
    # deploy time (deployment.build_application) — the graph's nodes
    # each get their own deployment + replica set.
    return driver.bind(spec, dict(apps))
