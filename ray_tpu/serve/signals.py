"""Derived operational signals over the telemetry history plane.

The time-series store (util/timeseries) retains raw series; this module
turns them into the signals the control plane and operators act on:

  * ``ArrivalSignal`` — an EWMA arrival rate plus its least-squares
    slope, fed with cumulative arrival counts.  The controller's
    autoscaler consumes the slope to scale up while the queue is still
    empty (decision reason ``"arrival_slope"``): arrival rate LEADS
    queue age, which leads latency — reacting to the leading signal
    buys a replica's startup time before the SLO is at risk.
  * ``derived_signals`` — per-process SLO burn rate, shed rate and
    request rate computed from the driver-side store, for the dashboard
    and ``raytpu top``.
"""

from __future__ import annotations

import collections
import math
from typing import Any, Dict, Optional


class ArrivalSignal:
    """EWMA arrival rate + slope from a cumulative arrival count.

    ``observe(ts, cumulative)`` feeds one observation (timestamps from
    any monotone clock; cumulative counts are reset-tolerant — a total
    that went backwards is treated as a restart, the new total being
    the count since reset).  ``rate()`` is the current EWMA in
    arrivals/s; ``slope()`` the least-squares slope of the EWMA over
    the trailing window, in arrivals/s per second."""

    def __init__(self, half_life_s: float = 2.0,
                 window_s: float = 5.0):
        if half_life_s <= 0:
            raise ValueError("half_life_s must be positive")
        if window_s <= 0:
            raise ValueError("window_s must be positive")
        self.half_life_s = float(half_life_s)
        self.window_s = float(window_s)
        self._last: Optional[tuple] = None  # (ts, cumulative)
        self._ewma = 0.0
        self._points: "collections.deque" = collections.deque()

    def observe(self, ts: float, cumulative: float) -> None:
        last = self._last
        self._last = (ts, cumulative)
        if last is None:
            return
        dt = ts - last[0]
        if dt <= 0:
            return
        delta = (cumulative if cumulative < last[1]
                 else cumulative - last[1])
        inst = delta / dt
        # Half-life-parameterised smoothing: after half_life_s of
        # observations the old rate contributes 50%.
        alpha = 1.0 - math.pow(0.5, dt / self.half_life_s)
        self._ewma += alpha * (inst - self._ewma)
        self._points.append((ts, self._ewma))
        horizon = ts - self.window_s
        while self._points and self._points[0][0] < horizon:
            self._points.popleft()

    def rate(self) -> float:
        return self._ewma

    def slope(self) -> float:
        pts = self._points
        n = len(pts)
        if n < 3:
            return 0.0  # not enough evidence to call a trend
        t0 = pts[0][0]
        sx = sy = sxx = sxy = 0.0
        for t, r in pts:
            x = t - t0
            sx += x
            sy += r
            sxx += x * x
            sxy += x * r
        denom = n * sxx - sx * sx
        if denom <= 0:
            return 0.0
        return (n * sxy - sx * sy) / denom


def _window_rate(series: list, window_s: float) -> float:
    """Summed counter deltas over the window / window seconds."""
    total = sum(p.get("delta", 0.0) for s in series for p in s["points"])
    return total / window_s if window_s > 0 else 0.0


def derived_signals(window_s: float = 60.0) -> Dict[str, Dict[str, Any]]:
    """Per-process operational signals from the driver-side store:

    ``{proc: {"request_rate", "shed_rate", "slo_burn_rate"}}``

    where slo_burn_rate is the fraction of terminal requests in the
    window that missed their SLO (0.0 when none terminated) and the
    rates are requests/second over the window."""
    import time

    from ray_tpu.util import timeseries

    since = time.time() - float(window_s)
    payload = timeseries.query(family="raytpu_serve_", since=since,
                               step=timeseries._rings[0][0])
    by_proc: Dict[str, Dict[str, list]] = {}
    for s in payload["series"]:
        by_proc.setdefault(s["proc"], {}).setdefault(
            s["family"], []).append(s)
    out: Dict[str, Dict[str, Any]] = {}
    for proc, fams in sorted(by_proc.items()):
        arrived = _window_rate(
            fams.get("raytpu_serve_requests_arrived_total", []), window_s)
        shed = _window_rate(fams.get("raytpu_serve_shed_total", []),
                            window_s)
        met = missed = 0.0
        for s in fams.get("raytpu_serve_request_slo_total", []):
            total = sum(p.get("delta", 0.0) for p in s["points"])
            if s["tags"].get("outcome") == "met":
                met += total
            else:
                missed += total
        terminal = met + missed
        out[proc] = {
            "request_rate": arrived,
            "shed_rate": shed,
            "slo_burn_rate": (missed / terminal) if terminal else 0.0,
        }
    return out
