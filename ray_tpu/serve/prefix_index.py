"""Radix-tree prefix cache over the paged KV pool.

Production chat/RAG traffic shares system prompts and conversation
prefixes; without a prefix cache every request re-prefills from token 0
(and the serve-plane failover replay re-prefills the WHOLE spliced
prompt).  This module is the index side of the fix: a radix tree keyed
on page-sized token chunks whose nodes each own exactly one KV page of
the engine's paged pool, with borrow refcounts and LRU eviction.

Ownership/refcount model (the invariant tests assert):

  * Every physical page is in exactly ONE of three places: the
    engine's free list, this index (``pages()``), or a slot's
    allocation (``_slot_pages``).  A *borrowed* page is a cached page
    additionally referenced by one or more slots' block tables — it
    stays owned by the index and never enters the free list directly.
  * ``refs`` counts live borrowers (slots currently mapping the page).
    The cache's own hold is implicit: a node with ``refs == 0`` is
    merely *evictable*, not free.
  * Only full pages are cached, and prefill resumes at the hit
    boundary, so in-flight writes always target positions at or past
    the first uncached page — shared pages are immutable by
    construction.  The single exception is an exact full-prompt hit
    (the last-token re-run lands inside the deepest shared page);
    the engine COW-splits that page before scheduling (see
    ``LLMEngine._admit_slot_for``).

Eviction is refcount-0 LRU over *leaves* only (an interior node's page
backs every cached suffix under it), cascading: evicting a leaf may
expose its parent as the next candidate.  The engine calls ``evict``
from ``_alloc_slot_pages`` under pool pressure, so cached pages never
starve admission.

Cache-aware routing rides ``summary()``: a compact list of chained
CRC32 hashes of the tree's paths, published over the controller's
long-poll broadcast table.  The router recomputes the same chain over
an incoming prompt (``match_depth``) and prefers the replica holding
the longest prefix.  CRC32 (not ``hash()``) because the chain must be
stable across processes — Python's string hashing is salted per
process.
"""

from __future__ import annotations

import itertools
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Set, Tuple


def _chunk_hash(chunk: Sequence[int], parent_hash: int) -> int:
    """Chained CRC32 over one page-sized token chunk.  The chain makes
    each hash identify the whole PATH (prefix), not just the chunk, so
    a flat hash set can answer "how deep does this prompt match"."""
    data = ",".join(str(int(t)) for t in chunk).encode()
    return zlib.crc32(data, parent_hash)


def prefix_hashes(tokens: Sequence[int], page_size: int,
                  max_depth: Optional[int] = None) -> List[int]:
    """Chained hashes of every full-page prefix of ``tokens`` (depth 1
    = first page, …).  Shared by the index (publisher) and the router
    (matcher)."""
    out: List[int] = []
    h = 0
    depth = len(tokens) // page_size
    if max_depth is not None:
        depth = min(depth, max_depth)
    for k in range(depth):
        h = _chunk_hash(tokens[k * page_size:(k + 1) * page_size], h)
        out.append(h)
    return out


def match_depth(tokens: Sequence[int], summary: Optional[dict]) -> int:
    """Longest cached prefix (in TOKENS) a replica's published summary
    claims for this prompt; 0 when the summary is absent/foreign.
    Deliberately tolerant: a summary is a hint for routing, never a
    correctness input (the engine re-matches exactly on admission)."""
    if not isinstance(summary, dict):
        return 0
    page = summary.get("page")
    hashes = summary.get("hashes")
    if not isinstance(page, int) or page <= 0 or not hashes:
        return 0
    have = set(hashes)
    best = 0
    for depth, h in enumerate(prefix_hashes(tokens, page), start=1):
        if h in have:
            best = depth * page
    return best


class _Node:
    __slots__ = ("chunk", "page", "hash", "parent", "children", "refs",
                 "leases", "last_used")

    def __init__(self, chunk: Tuple[int, ...], page: int, h: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk
        self.page = page
        self.hash = h
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.refs = 0  # live borrowers (slots), NOT the cache's hold
        self.leases = 0  # in-flight migration leases (kv_transfer)
        self.last_used = 0


class PrefixIndex:
    """Radix tree of full KV pages keyed on page-sized token chunks.

    Thread-safe; the engine loop is the only writer in practice but
    ``summary()``/``stats()`` are read from replica push threads."""

    def __init__(self, page_size: int):
        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.page_size = page_size
        self._root_children: Dict[Tuple[int, ...], _Node] = {}
        self._by_page: Dict[int, _Node] = {}
        self._clock = itertools.count(1)
        self._lock = threading.Lock()
        self.evicted_total = 0
        self.inserted_total = 0

    # -- queries -----------------------------------------------------------

    @property
    def cached_pages(self) -> int:
        return len(self._by_page)

    def pages(self) -> Set[int]:
        """The set of physical pages this index owns (for the pool
        accounting invariant: free ∪ cached ∪ slot-owned = pool, with
        borrowed = cached ∩ slot-mapped)."""
        with self._lock:
            return set(self._by_page)

    def refcount(self, page: int) -> int:
        with self._lock:
            node = self._by_page.get(page)
            return -1 if node is None else node.refs

    def _match_locked(self, tokens: Sequence[int]) -> List[_Node]:
        nodes: List[_Node] = []
        children = self._root_children
        for k in range(len(tokens) // self.page_size):
            chunk = tuple(
                int(t) for t in
                tokens[k * self.page_size:(k + 1) * self.page_size])
            node = children.get(chunk)
            if node is None:
                break
            nodes.append(node)
            children = node.children
        return nodes

    # -- borrow / return ---------------------------------------------------

    def acquire(self, tokens: Sequence[int]) -> List[int]:
        """Longest cached full-page prefix of ``tokens``: bump each
        matched node's refcount (pinning it and, transitively, its
        ancestors against eviction) and return the page ids in path
        order.  Caller must ``release`` exactly these pages."""
        with self._lock:
            nodes = self._match_locked(tokens)
            stamp = next(self._clock)
            for node in nodes:
                node.refs += 1
                node.last_used = stamp
            return [node.page for node in nodes]

    def release(self, pages: Sequence[int]) -> None:
        """Return borrowed pages (refcount -1 each).  Pages evicted
        while borrowed cannot exist (refs > 0 pins them), so an unknown
        page here is a double-free bug — raise, don't mask."""
        with self._lock:
            stamp = next(self._clock)
            for p in pages:
                node = self._by_page.get(p)
                if node is None or node.refs <= 0:
                    raise RuntimeError(
                        f"prefix cache: release of page {p} not "
                        f"borrowed (refcount underflow)")
                node.refs -= 1
                node.last_used = stamp

    # -- migration leases --------------------------------------------------

    def lease_acquire(self, tokens: Sequence[int]) -> List[int]:
        """Pin the longest cached full-page prefix of ``tokens`` under a
        migration lease (kv_transfer export).  Like ``acquire`` but on a
        separate counter: leases pin pages against eviction without
        looking like slot borrowers, so the free ∪ cached ∪ slot-owned
        pool invariant keeps holding (leased pages stay cached).  Caller
        must ``lease_release`` exactly these pages — including on
        cancel/failure paths."""
        with self._lock:
            nodes = self._match_locked(tokens)
            stamp = next(self._clock)
            for node in nodes:
                node.leases += 1
                node.last_used = stamp
            return [node.page for node in nodes]

    def lease_release(self, pages: Sequence[int]) -> None:
        """Drop a migration lease (one per page).  Leased pages cannot
        be evicted, so an unknown page here is a lease-accounting bug —
        raise, don't mask."""
        with self._lock:
            stamp = next(self._clock)
            for p in pages:
                node = self._by_page.get(p)
                if node is None or node.leases <= 0:
                    raise RuntimeError(
                        f"prefix cache: lease release of page {p} not "
                        f"leased (lease underflow)")
                node.leases -= 1
                node.last_used = stamp

    def leased_pages(self) -> Set[int]:
        """Pages currently pinned by at least one migration lease."""
        with self._lock:
            return {p for p, n in self._by_page.items() if n.leases > 0}

    # -- population --------------------------------------------------------

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> Set[int]:
        """Offer the full-page prefix of ``tokens`` for caching, backed
        by ``pages`` (page k holds tokens [k*page, (k+1)*page)).  For
        each depth: an existing node (same chunk) keeps its page and
        the offered one is NOT adopted; a missing node adopts the
        offered page with refs=0.  Returns the set of adopted page ids
        — the caller frees the rest.  Adoption stops at the first depth
        without an offered page."""
        adopted: Set[int] = set()
        with self._lock:
            stamp = next(self._clock)
            children = self._root_children
            parent: Optional[_Node] = None
            depth = min(len(tokens) // self.page_size, len(pages))
            for k in range(depth):
                chunk = tuple(
                    int(t) for t in
                    tokens[k * self.page_size:(k + 1) * self.page_size])
                node = children.get(chunk)
                if node is None:
                    page = pages[k]
                    if page in self._by_page:  # defensive: never alias
                        break
                    h = _chunk_hash(chunk, parent.hash if parent else 0)
                    node = _Node(chunk, page, h, parent)
                    children[chunk] = node
                    self._by_page[page] = node
                    self.inserted_total += 1
                    adopted.add(page)
                node.last_used = stamp
                parent = node
                children = node.children
        return adopted

    # -- eviction ----------------------------------------------------------

    def evict(self, n: int) -> List[int]:
        """Free up to ``n`` pages: refcount-0 LRU over leaves,
        cascading (an evicted leaf may expose its parent).  Returns the
        freed page ids — the caller returns them to the pool."""
        freed: List[int] = []
        with self._lock:
            while len(freed) < n:
                victim: Optional[_Node] = None
                for node in self._by_page.values():
                    # leases pin against eviction exactly like borrows:
                    # an in-flight migration export must never watch its
                    # source pages get recycled under it.
                    if (node.refs == 0 and node.leases == 0
                            and not node.children):
                        if victim is None or node.last_used < victim.last_used:
                            victim = node
                if victim is None:
                    break
                siblings = (victim.parent.children if victim.parent
                            else self._root_children)
                del siblings[victim.chunk]
                del self._by_page[victim.page]
                freed.append(victim.page)
            self.evicted_total += len(freed)
        return freed

    # -- routing summary ---------------------------------------------------

    def summary(self, max_entries: int = 256) -> dict:
        """Compact cross-process view for cache-aware routing: the
        chained path hashes of the most recently used nodes.  Bounded
        (LRU-most-recent first) so the broadcast table stays small."""
        with self._lock:
            nodes = sorted(self._by_page.values(),
                           key=lambda n: -n.last_used)[:max_entries]
            return {"page": self.page_size,
                    "hashes": [n.hash for n in nodes]}

    def hot_paths(self, max_pages: int = 256) -> List[dict]:
        """Recency-ordered root-to-node paths for prefix migration: each
        entry is ``{"tokens", "pages", "hashes"}`` for one full cached
        path (deepest hot node first), deduplicated so a path that is a
        prefix of an earlier (hotter) one is skipped.  Bounded by the
        total page count across returned paths."""
        with self._lock:
            nodes = sorted(self._by_page.values(),
                           key=lambda n: -n.last_used)
        out: List[dict] = []
        covered: Set[int] = set()
        budget = max_pages
        for node in nodes:
            if node.page in covered:
                continue
            path: List[_Node] = []
            cur: Optional[_Node] = node
            while cur is not None:
                path.append(cur)
                cur = cur.parent
            path.reverse()
            if len(path) > budget:
                continue
            tokens: List[int] = []
            for p in path:
                tokens.extend(p.chunk)
            out.append({
                "tokens": tokens,
                "pages": [p.page for p in path],
                "hashes": [p.hash for p in path],
            })
            covered.update(p.page for p in path)
            budget -= len(path)
            if budget <= 0:
                break
        return out

    def audit_snapshot(self) -> dict:
        """Consistent view for the doctor plane (serve/audit): per-page
        refcounts/lease counts from the page index plus a reachability
        walk from the root.  ``pages[p]["reachable"]`` is False for an
        orphaned node (indexed but detached from the tree);
        ``unindexed`` lists pages a root walk reaches that the page
        index has lost — both are corruption, caught by different
        halves of kv.trie_integrity."""
        with self._lock:
            reachable: Set[int] = set()
            stack = list(self._root_children.values())
            while stack:
                node = stack.pop()
                reachable.add(node.page)
                stack.extend(node.children.values())
            return {
                "pages": {p: {"refs": n.refs, "leases": n.leases,
                              "reachable": p in reachable}
                          for p, n in self._by_page.items()},
                "unindexed": sorted(reachable - set(self._by_page)),
            }

    def stats(self) -> dict:
        with self._lock:
            return {
                "cached_pages": len(self._by_page),
                "evicted_pages": self.evicted_total,
                "inserted_pages": self.inserted_total,
                "borrowed_refs": sum(n.refs
                                     for n in self._by_page.values()),
                "leased_pages": sum(1 for n in self._by_page.values()
                                    if n.leases > 0),
            }
