"""Replica actor: hosts one copy of a deployment's user callable.

Parity with the reference (ray: python/ray/serve/_private/replica.py —
RayServeReplica:494): constructs the user class, counts ongoing
requests, pushes autoscaling metrics to the controller, supports
``reconfigure(user_config)`` and user-defined ``check_health``.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.core.actor import method
from ray_tpu.core.exceptions import PreemptedError
from ray_tpu.serve.deployment import _HandlePlaceholder
from ray_tpu.util import tracing

_TELEMETRY = None


def _telemetry():
    """Replica metric singletons (re-registered on refetch — see
    llm_engine._telemetry for the registry-clear rationale)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "latency": metrics.Histogram(
                "raytpu_serve_request_latency_seconds",
                "End-to-end user-code latency inside the replica, by "
                "deployment.",
                boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                            5.0, 10.0, 60.0],
                tag_keys=("deployment",),
            ),
            "ongoing": metrics.Gauge(
                "raytpu_serve_replica_ongoing",
                "Requests currently executing, by replica.",
                tag_keys=("deployment", "replica"),
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


def _resolve_placeholders(value: Any) -> Any:
    from ray_tpu.serve.handle import DeploymentHandle

    if isinstance(value, _HandlePlaceholder):
        return DeploymentHandle(value.deployment_name, value.app_name)
    if isinstance(value, (list, tuple)):
        return type(value)(_resolve_placeholders(v) for v in value)
    if isinstance(value, dict):
        return {k: _resolve_placeholders(v) for k, v in value.items()}
    return value


class ReplicaActor:
    """The actor class every deployment replica runs as."""

    def __init__(self, app_name: str, deployment_name: str, replica_id: str,
                 func_or_class: Any, init_args: tuple, init_kwargs: dict,
                 user_config: Any, metrics_interval_s: float = 0.0,
                 shard_group: Optional[dict] = None,
                 disagg: Optional[dict] = None):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._tm = _telemetry()
        self._tags = {"deployment": deployment_name, "replica": replica_id}
        init_args = _resolve_placeholders(init_args)
        init_kwargs = _resolve_placeholders(init_kwargs)
        if shard_group is not None:
            # Rank 0 of a multi-host shard group: install the ambient
            # context BEFORE the user callable constructs, so an
            # engine-hosting callable builds its hybrid serving mesh
            # (serve/shard_group.py; LLMServer reads it).
            from ray_tpu.serve.shard_group import (
                ShardGroupContext,
                set_shard_group,
            )

            set_shard_group(ShardGroupContext(**shard_group))
        if disagg is not None:
            # Disaggregated prefill/decode role (config.disagg):
            # install the ambient context BEFORE the user callable
            # constructs, same pattern as the shard group — LLMServer
            # reads it to run the KV-migration handoff protocol.
            from ray_tpu.serve.kv_transfer import DisaggContext, set_disagg

            set_disagg(DisaggContext(**disagg))
        if inspect.isclass(func_or_class):
            self._callable = func_or_class(*init_args, **init_kwargs)
        else:
            if init_args or init_kwargs:
                raise ValueError(
                    "function deployments take no bind() arguments"
                )
            self._callable = func_or_class
        if user_config is not None:
            self.reconfigure(user_config)
        # Preemption-aware drain: once flipped the replica rejects new
        # data-plane requests with PreemptedError (the router retries
        # them on a surviving replica) and reports DRAINING from
        # check_health so the controller starts a replacement.
        self._draining = False
        self._install_sigterm_drain()
        self._metrics_stop = threading.Event()
        # Prefix-cache routing: a callable exposing prefix_summary()
        # (LLMServer over a prefix-cached engine) gets the push loop
        # even without an autoscaling metrics interval — the summary
        # rides the same thread, pushed only on change.
        self._last_prefix_summary = None
        _summary_fn = getattr(self._callable, "prefix_summary", None)
        try:
            # None at probe time = the cache is off for good (the flag
            # is construction-time config), so stay off the push path.
            self._pushes_summary = (callable(_summary_fn)
                                    and _summary_fn() is not None)
        except Exception:
            self._pushes_summary = False
        # LoRA multiplexing rides the same push thread: a callable
        # exposing adapter_summary() publishes its resident-adapter set
        # for adapter-affinity routing, pushed only on change.
        self._last_adapter_summary = None
        _adapter_fn = getattr(self._callable, "adapter_summary", None)
        try:
            self._pushes_adapters = (callable(_adapter_fn)
                                     and _adapter_fn() is not None)
        except Exception:
            self._pushes_adapters = False
        # SLO pressure signals for the autoscaler: a callable exposing
        # pressure() (LLMServer) reports its admission-queue age and
        # goodput ratio with every metrics push.
        _pressure_fn = getattr(self._callable, "pressure", None)
        self._pressure_fn = _pressure_fn if callable(_pressure_fn) else None
        if (metrics_interval_s > 0 or self._pushes_summary
                or self._pushes_adapters):
            threading.Thread(
                target=self._push_metrics_loop,
                args=(metrics_interval_s or 0.25,),
                daemon=True, name=f"metrics-{replica_id}",
            ).start()

    def _install_sigterm_drain(self) -> None:
        """Best-effort preemption notice: a SIGTERM (cloud preemption
        warning) drains the replica instead of letting it die hot with
        every stream attached.  Only installable from a process main
        thread (process-mode replicas); thread-mode replicas get the
        same behavior through the controller's drain_replica RPC."""
        import signal

        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _on_sigterm(signum, frame):
                threading.Thread(target=self.drain, daemon=True,
                                 name=f"drain-{self.replica_id}").start()
                if callable(prev):
                    prev(signum, frame)

            signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            pass

    def _reject_if_draining(self) -> None:
        if self._draining:
            raise PreemptedError(
                f"replica {self.replica_id} is draining: not accepting "
                f"new requests")

    # -- data plane --------------------------------------------------------

    def _target(self, method_name: str):
        if method_name == "__call__":
            if not callable(self._callable):
                raise TypeError(
                    f"deployment {self.deployment_name!r} is not "
                    f"callable — define __call__ or route to a named "
                    f"method"
                )
            return self._callable
        return getattr(self._callable, method_name)

    def handle_request(self, method_name: str, args: tuple, kwargs: dict,
                       metadata: dict = None):
        from ray_tpu.core import api
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.serve import multiplex as _mux
        from ray_tpu.serve import request_events as _reqev

        self._reject_if_draining()
        # Upstream DeploymentResponses arrive as refs nested inside the
        # args tuple — resolve them here (parity: the reference resolves
        # response args before invoking the user method).
        args = tuple(
            api.get(a) if isinstance(a, ObjectRef) else a for a in args
        )
        kwargs = {
            k: api.get(v) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        t0 = time.perf_counter()
        with self._lock:
            self._ongoing += 1
            self._total += 1
            self._tm["ongoing"].set(self._ongoing, tags=self._tags)
        mux_token = _mux._set_model_id(
            (metadata or {}).get("multiplexed_model_id", "")
        )
        # The router-minted request id becomes ambient context for the
        # user callable (same token pattern as the mux model id) —
        # LLMEngine.submit and any downstream handle call inherit it.
        rid_token = _reqev.set_request_id(
            (metadata or {}).get("request_id", "")
        )
        try:
            with tracing.span(
                    "serve.replica",
                    attributes={"deployment": self.deployment_name,
                                "replica": self.replica_id,
                                "method": method_name,
                                "request_id":
                                    (metadata or {}).get("request_id")}):
                result = self._target(method_name)(*args, **kwargs)
                if inspect.iscoroutine(result):
                    import asyncio

                    result = asyncio.run(result)
                return result
        finally:
            _reqev.reset_request_id(rid_token)
            _mux._reset_model_id(mux_token)
            self._tm["latency"].observe(
                time.perf_counter() - t0,
                tags={"deployment": self.deployment_name})
            with self._lock:
                self._ongoing -= 1
                self._tm["ongoing"].set(self._ongoing, tags=self._tags)

    async def handle_request_async(self, method_name: str, args: tuple,
                                   kwargs: dict, metadata: dict = None):
        """Async data plane: runs as a coroutine on the replica actor's
        event loop, so max_ongoing_requests requests interleave their
        awaits on ONE loop instead of one thread each (parity: the
        reference's replica is natively asyncio, replica.py:494)."""
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.serve import multiplex as _mux
        from ray_tpu.serve import request_events as _reqev

        self._reject_if_draining()
        # List comp, not genexp: a generator expression containing
        # ``await`` is an async generator, which tuple() rejects.
        args = tuple(
            [(await a) if isinstance(a, ObjectRef) else a for a in args]
        )
        kwargs = {
            k: (await v) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        t0 = time.perf_counter()
        with self._lock:
            self._ongoing += 1
            self._total += 1
            self._tm["ongoing"].set(self._ongoing, tags=self._tags)
        mux_token = _mux._set_model_id(
            (metadata or {}).get("multiplexed_model_id", "")
        )
        rid_token = _reqev.set_request_id(
            (metadata or {}).get("request_id", "")
        )
        try:
            # Metrics only on the async plane: a span context manager
            # around an await would leak its thread-local ctx across
            # every coroutine interleaved on the loop.
            target = self._target(method_name)
            # Per-METHOD dispatch: the deployment is announced async off
            # its __call__, but a sync named method must not run inline
            # on the shared event loop (it would freeze every
            # interleaved request, or deadlock if it blocks on another
            # coroutine's output) — push it to a thread.
            fn = (target if inspect.isroutine(target)
                  else getattr(target, "__call__", target))
            if inspect.iscoroutinefunction(fn):
                return await target(*args, **kwargs)
            import asyncio
            import contextvars
            import functools

            loop = asyncio.get_running_loop()
            # copy_context(): run_in_executor does not carry
            # contextvars to the worker thread — the request id (and
            # mux model id) must follow the sync target there.
            result = await loop.run_in_executor(
                None,
                functools.partial(contextvars.copy_context().run,
                                  functools.partial(target, *args,
                                                    **kwargs)))
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            _reqev.reset_request_id(rid_token)
            _mux._reset_model_id(mux_token)
            self._tm["latency"].observe(
                time.perf_counter() - t0,
                tags={"deployment": self.deployment_name})
            with self._lock:
                self._ongoing -= 1
                self._tm["ongoing"].set(self._ongoing, tags=self._tags)

    @method(num_returns="streaming")
    def handle_request_streaming(self, method_name: str, args: tuple,
                                 kwargs: dict, metadata: dict = None):
        """Streaming data plane: the user target returns an iterable
        (e.g. ``LLMServer.stream``) and each item rides back as one
        stream element.  A replica death or preemption seals the error
        AFTER every already-yielded item, so the consumer-side failover
        (handle.DeploymentResponseGenerator) resumes from exactly the
        delivered prefix."""
        from ray_tpu.core import api
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.serve import multiplex as _mux
        from ray_tpu.serve import request_events as _reqev
        from ray_tpu.utils.test_utils import fail_point

        self._reject_if_draining()
        fail_point("replica.stream")
        args = tuple(
            api.get(a) if isinstance(a, ObjectRef) else a for a in args
        )
        kwargs = {
            k: api.get(v) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        t0 = time.perf_counter()
        with self._lock:
            self._ongoing += 1
            self._total += 1
            self._tm["ongoing"].set(self._ongoing, tags=self._tags)
        mux_token = _mux._set_model_id(
            (metadata or {}).get("multiplexed_model_id", "")
        )
        rid_token = _reqev.set_request_id(
            (metadata or {}).get("request_id", "")
        )
        try:
            with tracing.span(
                    "serve.replica",
                    attributes={"deployment": self.deployment_name,
                                "replica": self.replica_id,
                                "method": method_name,
                                "streaming": True,
                                "request_id":
                                    (metadata or {}).get("request_id")}):
                for item in self._target(method_name)(*args, **kwargs):
                    yield item
        finally:
            _reqev.reset_request_id(rid_token)
            _mux._reset_model_id(mux_token)
            self._tm["latency"].observe(
                time.perf_counter() - t0,
                tags={"deployment": self.deployment_name})
            with self._lock:
                self._ongoing -= 1
                self._tm["ongoing"].set(self._ongoing, tags=self._tags)

    # -- control plane -----------------------------------------------------

    def drain(self, grace_s: float = 5.0) -> str:
        """Preemption notice (controller drain_replica RPC, SIGTERM, or
        a node-daemon maintenance event): stop accepting new requests
        and hand the notice down to the user callable's ``drain`` hook
        when it has one (LLMServer drains its engine — short requests
        finish, long ones are evicted with continuations).  Idempotent;
        returns the DRAINING health state."""
        with self._lock:
            already = self._draining
            self._draining = True
        if not already:
            fn = getattr(self._callable, "drain", None)
            if fn is not None:
                fn(grace_s)
        return "DRAINING"

    def get_metadata(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "ongoing": self._ongoing,
                "total": self._total,
            }

    def num_ongoing_requests(self) -> int:
        with self._lock:
            return self._ongoing

    def reconfigure(self, user_config: Any) -> None:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is None:
            raise ValueError(
                f"deployment {self.deployment_name!r} got user_config but "
                f"defines no reconfigure(config) method"
            )
        fn(user_config)

    def check_health(self):
        """True = healthy; the string "DRAINING" = alive but draining
        (the controller starts a replacement without tearing this
        replica out of the route table first); raises = unhealthy."""
        if self._draining:
            return "DRAINING"
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()  # raises on unhealthy (parity: serve health-check contract)
        return True

    def doctor(self, deep: bool = True) -> Optional[Dict[str, Any]]:
        """Run the invariant doctor on the user callable's engine
        (LLMServer.doctor → LLMEngine.doctor) and return its report;
        None when the callable has no doctor surface."""
        fn = getattr(self._callable, "doctor", None)
        if fn is None:
            return None
        return fn(deep=deep)

    def prepare_for_shutdown(self, timeout_s: float) -> None:
        """Drain: wait for ongoing requests to finish (parity:
        graceful_shutdown_timeout_s)."""
        self._metrics_stop.set()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return
            time.sleep(0.01)

    def _push_metrics_loop(self, interval_s: float) -> None:
        from ray_tpu.core import api
        from ray_tpu.serve.controller import CONTROLLER_NAME

        # Controller-outage tolerance: a failed push backs off
        # (capped-exponential) and RETRIES instead of killing the loop
        # — a controller crash would otherwise permanently silence this
        # replica's autoscaling signal and routing summaries even after
        # recovery.  The latest summary IS the buffer: on reconnect the
        # change-detection baselines reset so the new controller epoch
        # (whose adopted record may predate recent changes) gets a
        # fresh push of both summaries.
        backoff = interval_s or 0.05
        failing = False
        while not self._metrics_stop.wait(
                backoff if failing else interval_s):
            try:
                controller = api.get_actor(CONTROLLER_NAME)
                if failing:
                    failing = False
                    backoff = interval_s or 0.05
                    self._last_prefix_summary = None
                    self._last_adapter_summary = None
                qage, goodput, arrivals = 0.0, None, None
                if self._pressure_fn is not None:
                    try:
                        p = self._pressure_fn()
                        qage = float(p.get("queue_age_s") or 0.0)
                        goodput = p.get("goodput")
                        arrivals = p.get("arrivals")
                    except Exception:
                        pass
                controller.record_autoscaling_metric.remote(
                    self.app_name, self.deployment_name, self.replica_id,
                    self.num_ongoing_requests(), time.monotonic(),
                    qage, goodput, arrivals,
                )
                if self._pushes_summary:
                    try:
                        summary = self._callable.prefix_summary()
                    except Exception:
                        summary = None
                    if (summary is not None
                            and summary != self._last_prefix_summary):
                        self._last_prefix_summary = summary
                        controller.record_prefix_summary.remote(
                            self.app_name, self.deployment_name,
                            self.replica_id, summary,
                        )
                if self._pushes_adapters:
                    try:
                        asum = self._callable.adapter_summary()
                    except Exception:
                        asum = None
                    if (asum is not None
                            and asum != self._last_adapter_summary):
                        self._last_adapter_summary = asum
                        controller.record_adapter_summary.remote(
                            self.app_name, self.deployment_name,
                            self.replica_id, asum,
                        )
            except Exception:
                failing = True
                backoff = min(max(backoff, 0.05) * 2.0, 2.0)


class ShardMemberActor:
    """Rank >= 1 of a multi-host shard-group replica.

    Holds one placement-group bundle (one host's worth of chips) and
    answers health pings; its DEATH is the group's failure signal —
    the controller treats any member loss as whole-replica failure and
    routes the group through the PR-5 drain/failover path.  On real
    multi-host TPU this process additionally joins the group's
    jax.distributed runtime so rank 0's hybrid mesh spans its chips;
    on the CPU test backend the mesh lives over rank 0's virtual
    devices and this actor is purely the membership/fault unit."""

    def __init__(self, group_id: str, rank: int, size: int):
        self.group_id = group_id
        self.rank = rank
        self.size = size

    def ping(self) -> str:
        return f"{self.group_id}/{self.rank}"

    def get_metadata(self) -> Dict[str, Any]:
        return {"group_id": self.group_id, "rank": self.rank,
                "size": self.size}
