"""Paged pool of LoRA adapter weights for multi-tenant serving.

Thousands of fine-tuned variants cannot each be a resident model; they
CAN each be a few pages of LoRA factors.  This pool gives adapter
weights the same allocator discipline as the KV cache's paged pool:

  * fixed-size pages in one device array ``[num_pages + 1, page_elems]``
    (f32, or int8+per-page scale via the models/quant.py discipline);
    every adapter occupies exactly ``pages_per_adapter`` pages (fixed
    rank/targets per pool — see ops/segmented_lora.LoRAConfig), so the
    allocator never fragments;
  * borrow refcounts while any in-flight row uses an adapter, with
    refcount-0 LRU eviction under pressure and raise-on-underflow
    release — the PrefixIndex refcount contract (a double-release is a
    bug to surface, never mask);
  * load-once dedup by content hash: two adapter ids whose flattened
    factors are byte-identical share one page set (one upload, one
    eviction unit);
  * the LAST page index is the never-written all-zeros SCRATCH page:
    the null adapter (``adapter_id == ""``) and unused page-table rows
    gather exact zeros, which is what keeps base-model rows
    byte-identical to adapter-off serving.

The engine loop is the only caller of acquire/release/page_table;
``summary()``/``stats()`` are read from replica push threads, so all
state sits behind one lock.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.ops import segmented_lora as _sl

_TELEMETRY = None


def _telemetry():
    """Adapter-pool metric singletons, merged into the engine's
    telemetry dict (llm_engine._telemetry) so every family registers at
    engine construction and `check_metrics --require` sees them at zero
    before any adapter is ever loaded."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "adapter_pool_pages": metrics.Gauge(
                "raytpu_serve_adapter_pool_pages",
                "Fixed-size pages in the LoRA adapter pool (scratch "
                "page excluded)."),
            "adapter_resident": metrics.Gauge(
                "raytpu_serve_adapter_resident",
                "Adapter ids currently resident (backed by loaded "
                "pages; content-deduped ids each count once)."),
            "adapter_hits": metrics.Counter(
                "raytpu_serve_adapter_hits_total",
                "Adapter acquisitions served from resident pages "
                "(same id, or a content-hash dedup against another "
                "id's pages)."),
            "adapter_misses": metrics.Counter(
                "raytpu_serve_adapter_misses_total",
                "Adapter acquisitions that uploaded pages (first "
                "load, or a re-load after eviction)."),
            "adapter_evictions": metrics.Counter(
                "raytpu_serve_adapter_evictions_total",
                "Adapter page-sets evicted (refcount-0 LRU under "
                "pool pressure)."),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


class AdapterPoolPressure(RuntimeError):
    """Transient: every resident adapter is borrowed by an in-flight
    row, so nothing is evictable right now.  Callers back off and
    retry once borrows release (the engine re-queues the request)."""


class _Block:
    """One loaded (content-unique) adapter: its page set + borrows."""

    __slots__ = ("pages", "refs", "last_used", "ids")

    def __init__(self, pages: List[int]):
        self.pages = pages
        self.refs = 0
        self.last_used = 0
        self.ids: Set[str] = set()


class AdapterPool:
    def __init__(self, model_cfg: Any, lora_cfg: _sl.LoRAConfig, *,
                 num_pages: int = 0, page_elems: int = 8192,
                 max_batch_adapters: int = 8, int8: bool = False,
                 loader: Optional[Callable[[str], Any]] = None):
        if page_elems <= 0:
            raise ValueError(f"page_elems must be positive, got {page_elems}")
        self.model_cfg = model_cfg
        self.lora_cfg = lora_cfg
        self.page_elems = int(page_elems)
        self.elems = _sl.adapter_elems(model_cfg, lora_cfg)
        self.pages_per_adapter = -(-self.elems // self.page_elems)
        if num_pages <= 0:
            # Auto-size: room for 4 resident adapters — enough that the
            # tiny test configs exercise hits before eviction kicks in.
            num_pages = 4 * self.pages_per_adapter
        if num_pages < self.pages_per_adapter:
            raise ValueError(
                f"adapter pool of {num_pages} pages cannot hold one "
                f"adapter ({self.pages_per_adapter} pages of "
                f"{self.page_elems} elems for {self.elems} elems)")
        self.num_pages = int(num_pages)
        self.max_batch_adapters = int(max_batch_adapters)
        self.int8 = bool(int8)
        self._loader = loader or _sl.default_adapter_loader(
            model_cfg, lora_cfg)

        # Scratch page = index num_pages: zero-initialized, never
        # written (upload pads land on real pages only).
        if self.int8:
            self._device: Any = {
                "q": jnp.zeros((self.num_pages + 1, self.page_elems),
                               jnp.int8),
                "scale": jnp.ones((self.num_pages + 1, 1), jnp.float32),
            }
        else:
            self._device = jnp.zeros((self.num_pages + 1, self.page_elems),
                                     jnp.float32)
        self._scatter = jax.jit(
            lambda pool, ids, payload: pool.at[ids].set(payload),
            donate_argnums=(0,))
        self._scatter_q = jax.jit(
            lambda q, s, ids, qp, sp: (q.at[ids].set(qp),
                                       s.at[ids].set(sp)),
            donate_argnums=(0, 1))

        self._entries: Dict[str, str] = {}      # adapter_id -> content hash
        self._blocks: Dict[str, _Block] = {}    # content hash -> block
        self._free: List[int] = list(range(self.num_pages))
        self._clock = itertools.count(1)
        self._lock = threading.Lock()
        self.hits_total = 0
        self.misses_total = 0
        self.evictions_total = 0
        self._tm = _telemetry()
        self._tm["adapter_pool_pages"].set(self.num_pages)
        self._tm["adapter_resident"].set(0)

    # -- load / borrow -----------------------------------------------------

    def _load_flat(self, adapter_id: str) -> np.ndarray:
        flat = self._loader(adapter_id)
        if not isinstance(flat, np.ndarray) or flat.ndim != 1:
            flat = _sl.flatten_adapter(flat, self.model_cfg, self.lora_cfg)
        flat = np.asarray(flat, np.float32)
        if flat.shape != (self.elems,):
            raise ValueError(
                f"adapter {adapter_id!r}: loader produced {flat.shape}, "
                f"want ({self.elems},)")
        return flat

    def _set_resident_gauge_locked(self) -> None:
        self._tm["adapter_resident"].set(
            len({i for b in self._blocks.values() for i in b.ids}))

    def _evict_one_locked(self) -> bool:
        victim_h, victim = None, None
        for h, block in self._blocks.items():
            if block.refs == 0 and (
                    victim is None or block.last_used < victim.last_used):
                victim_h, victim = h, block
        if victim is None:
            return False
        del self._blocks[victim_h]
        self._free.extend(victim.pages)
        self.evictions_total += 1
        self._tm["adapter_evictions"].inc()
        self._set_resident_gauge_locked()
        return True

    def _upload_locked(self, pages: List[int], flat: np.ndarray) -> None:
        pp, pe = self.pages_per_adapter, self.page_elems
        payload = np.zeros((pp, pe), np.float32)
        payload.reshape(-1)[:self.elems] = flat
        ids = jnp.asarray(np.asarray(pages, np.int32))
        if self.int8:
            # Per-PAGE absmax via quant.quantize_tensor: pages become
            # the output-channel axis by transposing the payload.
            from ray_tpu.models.quant import quantize_tensor
            qd = quantize_tensor(jnp.asarray(payload.T))
            q, s = self._scatter_q(
                self._device["q"], self._device["scale"], ids,
                qd["q"].T, qd["scale"].reshape(-1, 1))
            self._device = {"q": q, "scale": s}
        else:
            self._device = self._scatter(self._device, ids,
                                         jnp.asarray(payload))

    def acquire(self, adapter_id: str) -> None:
        """Pin ``adapter_id``'s pages (loading them if absent) for one
        in-flight row.  Caller must ``release`` exactly once.  Raises
        AdapterPoolPressure when nothing is evictable — transient,
        retry after borrows drain."""
        if not adapter_id:
            return  # null adapter: scratch page, nothing to pin
        with self._lock:
            h = self._entries.get(adapter_id)
            flat = None
            if h is None:
                flat = self._load_flat(adapter_id)
                h = hashlib.sha1(flat.tobytes()).hexdigest()
                self._entries[adapter_id] = h
            block = self._blocks.get(h)
            stamp = next(self._clock)
            if block is not None:
                block.refs += 1
                block.last_used = stamp
                block.ids.add(adapter_id)
                self.hits_total += 1
                self._tm["adapter_hits"].inc()
                self._set_resident_gauge_locked()
                return
            if flat is None:  # known hash, pages evicted: re-load
                flat = self._load_flat(adapter_id)
            while len(self._free) < self.pages_per_adapter:
                if not self._evict_one_locked():
                    raise AdapterPoolPressure(
                        f"adapter pool: {adapter_id!r} needs "
                        f"{self.pages_per_adapter} pages, "
                        f"{len(self._free)} free and every resident "
                        f"adapter is borrowed")
            pages = [self._free.pop() for _ in
                     range(self.pages_per_adapter)]
            self._upload_locked(pages, flat)
            block = _Block(pages)
            block.refs = 1
            block.last_used = stamp
            block.ids.add(adapter_id)
            self._blocks[h] = block
            self.misses_total += 1
            self._tm["adapter_misses"].inc()
            self._set_resident_gauge_locked()

    def release(self, adapter_id: str) -> None:
        """Unpin one borrow.  An unknown or unborrowed id is a
        double-free bug — raise, don't mask (PrefixIndex contract)."""
        if not adapter_id:
            return
        with self._lock:
            h = self._entries.get(adapter_id)
            block = self._blocks.get(h) if h is not None else None
            if block is None or block.refs <= 0:
                raise RuntimeError(
                    f"adapter pool: release of {adapter_id!r} not "
                    f"borrowed (refcount underflow)")
            block.refs -= 1
            block.last_used = next(self._clock)

    def refcount(self, adapter_id: str) -> int:
        with self._lock:
            h = self._entries.get(adapter_id)
            block = self._blocks.get(h) if h is not None else None
            return -1 if block is None else block.refs

    # -- batch gather plan -------------------------------------------------

    @property
    def device_pool(self) -> Any:
        return self._device

    def page_table(self, batch_ids: Sequence[str]) -> np.ndarray:
        """[max_batch_adapters, pages_per_adapter] int32 gather plan:
        row 0 and every unused row point at the scratch page (exact
        zeros); row 1+j holds batch_ids[j]'s pages.  Every id must be
        resident (borrowed by the rows that reference it)."""
        K, pp = self.max_batch_adapters, self.pages_per_adapter
        if len(batch_ids) > K - 1:
            raise ValueError(
                f"{len(batch_ids)} adapters in one batch, pool allows "
                f"{K - 1} (max_batch_adapters={K} incl. the null row)")
        table = np.full((K, pp), self.num_pages, np.int32)  # scratch
        with self._lock:
            for j, aid in enumerate(batch_ids):
                h = self._entries.get(aid)
                block = self._blocks.get(h) if h is not None else None
                if block is None:
                    raise RuntimeError(
                        f"adapter pool: {aid!r} not resident at "
                        f"page_table time (borrow-before-batch bug)")
                table[1 + j] = block.pages
        return table

    # -- read-side surfaces ------------------------------------------------

    def resident_ids(self) -> List[str]:
        with self._lock:
            out: Set[str] = set()
            for block in self._blocks.values():
                out |= block.ids
            return sorted(out)

    def summary(self) -> dict:
        """Compact cross-process view for adapter-affinity routing,
        published on the controller broadcast table exactly like the
        prefix cache's summary()."""
        return {"adapters": self.resident_ids()}

    def audit_snapshot(self) -> dict:
        """Consistent allocator view for the doctor plane
        (serve/audit): the free list, every resident block's pages /
        refs / ids, and the id→content-hash map — enough to recount
        the pool partition and borrow balance externally."""
        with self._lock:
            return {
                "num_pages": self.num_pages,
                "pages_per_adapter": self.pages_per_adapter,
                "free": list(self._free),
                "blocks": {h: {"pages": list(b.pages), "refs": b.refs,
                               "ids": sorted(b.ids)}
                           for h, b in self._blocks.items()},
                "entries": dict(self._entries),
            }

    def stats(self) -> dict:
        with self._lock:
            resident = sorted(
                {i for b in self._blocks.values() for i in b.ids})
            looked = self.hits_total + self.misses_total
            return {
                "pool_pages": self.num_pages,
                "pages_free": len(self._free),
                "pages_per_adapter": self.pages_per_adapter,
                "resident": len(resident),
                "resident_ids": resident,
                "hits": self.hits_total,
                "misses": self.misses_total,
                "evictions": self.evictions_total,
                "hit_ratio": (self.hits_total / looked) if looked else 0.0,
                "borrowed_refs": sum(b.refs
                                     for b in self._blocks.values()),
            }
