"""Cross-replica KV page-migration plane (disaggregated serving).

Long prefills and decode steps contend for the same chips inside one
token-budget step; disaggregation gives each phase its own replicas and
streams finished KV pages between them.  This module is the transfer
plane those roles ride on:

  * **Wire format** — `encode_pages`/`decode_payload` serialize gathered
    KV pages (k/v ``[L, KVH, N, page, D]`` plus per-page scales for int8
    pools) either exactly or through the PR-9 style per-page int8
    quantization (absmax/127 scales, floored 1e-8).  Bytes-on-wire are
    accounted analytically (`parallel.collectives.page_transfer_wire_bytes`)
    so CPU emulation and a real DCN fabric report the same number.
  * **Content identity** — every transfer carries the chained-CRC32 path
    hashes (`prefix_index.prefix_hashes`) of its token prefix; the
    destination recomputes them before touching its pool, so both sides
    agree on exactly which prefix a page holds.
  * **Roles** — `DisaggContext` is the ambient per-replica role
    (installed by the ReplicaActor, same pattern as
    `serve/shard_group.py`); `MigrationHandoff` is the control-flow
    signal a prefill replica raises once pages have landed on a decode
    replica (a PreemptedError subclass, so the PR-5 failover machinery
    transports it and local recompute remains the universal fallback).

The engine-side verbs (lease → export → ingest → release) live on
`LLMEngine` — they must run on the engine loop thread because the cache
is donated between jitted dispatches.  The protocol invariant the tests
pin: pages under a migration lease are eviction-proof
(`prefix_index` skips them), and every lease is released on ALL paths —
success, failure, and cancel — so the pool accounting
free ∪ cached ∪ slot-owned (∪ leased ⊆ cached) always holds.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.core.exceptions import PreemptedError

_TELEMETRY = None


def _telemetry():
    """Migration/disagg metric singletons.  Merged into the engine's
    telemetry dict (`llm_engine._telemetry`) so the families register at
    engine construction and `check_metrics --require` sees them at zero
    before any migration happens."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "mig_pages": metrics.Counter(
                "raytpu_serve_kv_migration_pages_total",
                "KV pages moved between replica pools, by direction "
                "(out = exported under a migration lease, in = "
                "ingested into the local pool).",
                tag_keys=("direction",),
            ),
            "mig_bytes": metrics.Counter(
                "raytpu_serve_kv_migration_bytes_total",
                "Bytes-on-wire of KV page payloads (int8 page bytes + "
                "f32 per-page scales when quantized, raw dtype bytes "
                "when exact), by direction.  Analytic accounting "
                "(parallel.collectives.page_transfer_wire_bytes) so "
                "CPU emulation and real DCN report the same number.",
                tag_keys=("direction",),
            ),
            "mig_seconds": metrics.Histogram(
                "raytpu_serve_kv_migration_seconds",
                "Wall time of one migration verb on the engine loop "
                "(export = lease gather + host pull + encode; ingest "
                "= decode + scatter + trie insert).",
                boundaries=[0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                            0.1, 0.25, 0.5, 1.0, 2.5, 5.0],
                tag_keys=("op",),
            ),
            "disagg_handoffs": metrics.Counter(
                "raytpu_serve_disagg_handoffs_total",
                "Prefill-to-decode stream handoffs by outcome "
                "(migrated = pages landed and the stream resumed on "
                "the decode replica; failed = transfer aborted and "
                "the continuation replay recomputed locally; local = "
                "no decode target, served unified).",
                tag_keys=("outcome",),
            ),
            "disagg_requests": metrics.Counter(
                "raytpu_serve_disagg_requests_total",
                "Streamed requests entering a disaggregated "
                "deployment, by the serving replica's role.",
                tag_keys=("role",),
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


# -- ambient per-replica role (serve/shard_group.py pattern) ----------------

@dataclasses.dataclass(frozen=True)
class DisaggContext:
    """The replica's disaggregation role plus everything its LLMServer
    needs to run the handoff protocol.  Installed by the hosting
    ReplicaActor before the user callable constructs."""

    role: str = "unified"  # "prefill" | "decode" | "unified"
    transfer: str = "int8"  # page payload wire format ("int8"|"exact")
    handoff_after_tokens: int = 1
    migration_timeout_s: float = 5.0
    app_name: str = ""
    deployment_name: str = ""
    replica_id: str = ""


_LOCAL = threading.local()


def set_disagg(ctx: Optional[DisaggContext]) -> None:
    _LOCAL.ctx = ctx


def current_disagg() -> Optional[DisaggContext]:
    """The installing replica's DisaggContext, or None outside a
    disaggregated deployment."""
    return getattr(_LOCAL, "ctx", None)


class MigrationHandoff(PreemptedError):
    """The prefill replica finished its share of the request AND its KV
    pages landed on ``target_replica_id`` — the client generator should
    resume there (prefix-cache hit covers everything migrated) instead
    of recomputing.  Subclasses PreemptedError so the PR-5 failover
    path treats it as retriable with zero new machinery; if the target
    also fails, continuation replay still recomputes locally."""

    def __init__(self, reason: str = "stream handed off",
                 continuation: Optional[dict] = None,
                 target_replica_id: str = ""):
        self.target_replica_id = target_replica_id
        super().__init__(reason, continuation)

    def __reduce__(self):
        return (type(self),
                (self.reason, self.continuation, self.target_replica_id))


# -- page payload codec -----------------------------------------------------

def quantize_page_payload(pages: np.ndarray):
    """``[L, KVH, N, page, D]`` float pages → (int8 pages,
    ``[L, KVH, N]`` f32 per-page absmax scales) — the host-side mirror
    of the int8 KV pool's write-side quant (models/llama.py
    ``_quant_pages``): scale = absmax/127 floored at 1e-8."""
    a = np.max(np.abs(pages.astype(np.float32)), axis=(3, 4))
    scale = np.maximum(a / 127.0, 1e-8).astype(np.float32)
    q = np.clip(np.rint(pages.astype(np.float32)
                        / scale[..., None, None]), -127, 127)
    return q.astype(np.int8), scale


def dequantize_page_payload(q: np.ndarray, scale: np.ndarray,
                            dtype: Any) -> np.ndarray:
    """Inverse of `quantize_page_payload` (into the pool's dtype)."""
    return (q.astype(np.float32) * scale[..., None, None]).astype(dtype)


def encode_pages(gathered: Dict[str, np.ndarray], *,
                 tokens: Sequence[int], page_size: int,
                 mode: str = "int8") -> Dict[str, Any]:
    """Build one transfer dict from host-gathered pages.

    ``gathered``: "k"/"v" ``[L, KVH, N, page, D]`` in the source pool's
    storage dtype; int8 pools also carry "k_scale"/"v_scale" in the
    pool's page-major layout ``[L, N, KVH, 1]`` (converted here to the
    canonical ``[L, KVH, N]``).  ``mode`` "exact" ships the storage
    bytes as-is; "int8" quantizes float payloads per page (an int8
    source is already quantized — no second quantization)."""
    if mode not in ("int8", "exact"):
        raise ValueError(f"transfer mode must be 'int8' or 'exact', "
                         f"got {mode!r}")
    from ray_tpu.parallel.collectives import page_transfer_wire_bytes
    from ray_tpu.serve.prefix_index import prefix_hashes

    k, v = np.asarray(gathered["k"]), np.asarray(gathered["v"])
    L, KVH, N, page, D = k.shape
    if page != page_size or N * page_size != len(tokens):
        raise ValueError(
            f"payload shape {k.shape} does not cover {len(tokens)} "
            f"tokens at page_size={page_size}")
    out: Dict[str, Any] = {
        "version": 1,
        "page_size": page_size,
        "tokens": [int(t) for t in tokens],
        "hashes": prefix_hashes(tokens, page_size),
        "src_dtype": str(k.dtype),
    }
    if "k_scale" in gathered:
        # int8 source pool: payload is already quantized; reshape the
        # page-major scale columns [L, N, KVH, 1] → canonical [L, KVH, N].
        def canon(s):
            return np.ascontiguousarray(
                np.squeeze(np.asarray(s), -1).transpose(0, 2, 1)
            ).astype(np.float32)

        out.update(mode="int8", k=k, v=v,
                   k_scale=canon(gathered["k_scale"]),
                   v_scale=canon(gathered["v_scale"]))
    elif mode == "int8":
        qk, sk = quantize_page_payload(k)
        qv, sv = quantize_page_payload(v)
        out.update(mode="int8", k=qk, v=qv, k_scale=sk, v_scale=sv)
    else:
        out.update(mode="exact", k=k, v=v)
    elements = L * KVH * page * D
    quantized = out["mode"] == "int8"
    out["wire_bytes"] = 2 * page_transfer_wire_bytes(
        N, elements, quantized=quantized,
        itemsize=k.dtype.itemsize, scales_per_page=L * KVH)
    return out


def decode_payload(transfer: Dict[str, Any],
                   pool_quantized: bool, pool_dtype: Any,
                   start_page: int = 0,
                   end_page: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Transfer dict → arrays in the DESTINATION pool's storage layout,
    sliced to pages ``[start_page, end_page)`` (the destination skips
    depths it already caches).  Handles every source×dest combination:
    exact float ↔ float pools pass through, int8 payloads dequantize
    into float pools, float payloads quantize into int8 pools, and
    int8 → int8 ships raw bytes + scales with no requantization."""
    sl = slice(start_page, end_page)
    k = np.asarray(transfer["k"])[:, :, sl]
    v = np.asarray(transfer["v"])[:, :, sl]
    quant_payload = transfer["mode"] == "int8"
    if quant_payload:
        ks = np.asarray(transfer["k_scale"])[:, :, sl]
        vs = np.asarray(transfer["v_scale"])[:, :, sl]
    if pool_quantized:
        if not quant_payload:
            k, ks = quantize_page_payload(k)
            v, vs = quantize_page_payload(v)
        # canonical [L, KVH, n] scales → pool page-major [L, n, KVH, 1]
        def pool_scale(s):
            return np.ascontiguousarray(
                s.transpose(0, 2, 1))[..., None].astype(np.float32)

        return {"k": k.astype(np.int8), "v": v.astype(np.int8),
                "k_scale": pool_scale(ks), "v_scale": pool_scale(vs)}
    if quant_payload:
        return {"k": dequantize_page_payload(k, ks, pool_dtype),
                "v": dequantize_page_payload(v, vs, pool_dtype)}
    return {"k": k.astype(pool_dtype), "v": v.astype(pool_dtype)}


def transfer_num_pages(transfer: Dict[str, Any]) -> int:
    return int(np.asarray(transfer["k"]).shape[2])


def verify_transfer(transfer: Dict[str, Any]) -> List[int]:
    """Recompute the chained-CRC32 path hashes over the transfer's
    tokens and check them against the sender's — content identity is
    established BEFORE any page touches the local pool.  Returns the
    verified hash chain."""
    from ray_tpu.serve.prefix_index import prefix_hashes

    page = int(transfer["page_size"])
    tokens = transfer["tokens"]
    expect = prefix_hashes(tokens, page)
    got = [int(h) for h in transfer["hashes"]]
    if got != expect:
        raise ValueError(
            f"kv transfer content-identity mismatch: sender hashes "
            f"{got[:4]}... != recomputed {expect[:4]}... "
            f"({len(tokens)} tokens, page={page})")
    n = transfer_num_pages(transfer)
    if n != len(tokens) // page or n != len(expect):
        raise ValueError(
            f"kv transfer page count {n} does not match "
            f"{len(tokens)} tokens at page={page}")
    return expect
