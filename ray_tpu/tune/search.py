"""Search spaces + variant generation.

Parity with the reference's basic search layer (ray: python/ray/tune/
search/basic_variant.py — grid/random variant expansion;
tune/search/sample.py — Domain objects uniform/loguniform/choice/randint).
Advanced optimizers (Optuna/HyperOpt/...) plug in behind the same
``SearchAlgorithm.suggest`` seam.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclasses.dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclasses.dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclasses.dataclass
class RandInt(Domain):
    low: int
    high: int  # exclusive

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclasses.dataclass
class QUniform(Domain):
    low: float
    high: float
    q: float

    def sample(self, rng):
        return round(rng.uniform(self.low, self.high) / self.q) * self.q


@dataclasses.dataclass
class Choice(Domain):
    categories: List[Any]

    def sample(self, rng):
        return rng.choice(self.categories)


@dataclasses.dataclass
class GridSearch:
    values: List[Any]


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def quniform(low: float, high: float, q: float) -> QUniform:
    return QUniform(low, high, q)


def choice(categories: List[Any]) -> Choice:
    return Choice(list(categories))


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


def sample_from(fn: Callable[[Dict], Any]):
    return _SampleFrom(fn)


@dataclasses.dataclass
class _SampleFrom(Domain):
    fn: Callable[[Dict], Any]

    def sample(self, rng):
        return self.fn({})


class BasicVariantGenerator:
    """Grid axes fully expanded × num_samples random draws of the rest
    (parity: basic_variant.py semantics)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grid_values = [self.param_space[k].values for k in grid_keys]
        grids = list(itertools.product(*grid_values)) if grid_keys else [()]
        for _ in range(self.num_samples):
            for combo in grids:
                cfg: Dict[str, Any] = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                yield cfg
