"""Trial state + the in-trial session channel.

Parity with the reference's Trial FSM (ray: python/ray/tune/experiment/
trial.py:307 — PENDING/RUNNING/PAUSED/TERMINATED/ERROR) and the
session.report channel (ray: python/ray/air/session.py,
train/_internal/session.py:612 — workers stream metrics/checkpoints to
the driver).  Within our in-process runtime the channel is a thread-safe
queue registry keyed by trial id.
"""

from __future__ import annotations

import dataclasses
import queue as _queue
import threading
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    results: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    checkpoint: Any = None  # latest reported checkpoint (dict)
    actor: Any = None
    run_ref: Any = None
    restore_from: Any = None  # checkpoint to hand the next (re)start

    def last_result(self) -> Optional[Dict[str, Any]]:
        return self.results[-1] if self.results else None

    def best_metric(self, metric: str, mode: str) -> Optional[float]:
        vals = [r[metric] for r in self.results if metric in r]
        if not vals:
            return None
        return max(vals) if mode == "max" else min(vals)


class StopTrial(Exception):
    """Raised inside a trial when the scheduler decided to stop it."""


class _SessionChannel:
    """report()/get_checkpoint() plumbing between trial threads and the
    controller.  One registry per process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._queues: Dict[str, _queue.Queue] = {}
        self._stop_flags: Dict[str, threading.Event] = {}
        self._restore: Dict[str, Any] = {}
        self._stop_criteria: Dict[str, Dict[str, float]] = {}
        self._report_counts: Dict[str, int] = {}
        self._local = threading.local()

    # controller side -----------------------------------------------------

    def register(self, trial_id: str, restore_checkpoint: Any = None,
                 stop_criteria: Optional[Dict[str, float]] = None):
        with self._lock:
            self._queues[trial_id] = _queue.Queue()
            self._stop_flags[trial_id] = threading.Event()
            self._restore[trial_id] = restore_checkpoint
            self._stop_criteria[trial_id] = dict(stop_criteria or {})
            self._report_counts[trial_id] = 0

    def unregister(self, trial_id: str):
        with self._lock:
            self._queues.pop(trial_id, None)
            self._stop_flags.pop(trial_id, None)
            self._restore.pop(trial_id, None)
            self._stop_criteria.pop(trial_id, None)
            self._report_counts.pop(trial_id, None)

    def request_stop(self, trial_id: str):
        with self._lock:
            flag = self._stop_flags.get(trial_id)
        if flag is not None:
            flag.set()

    def drain(self, trial_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            q = self._queues.get(trial_id)
        out = []
        if q is None:
            return out
        while True:
            try:
                out.append(q.get_nowait())
            except _queue.Empty:
                return out

    # trial side ----------------------------------------------------------

    def bind(self, trial_id: str):
        self._local.trial_id = trial_id

    def current_trial_id(self) -> Optional[str]:
        return getattr(self._local, "trial_id", None)

    def report(self, metrics: Dict[str, Any], checkpoint: Any = None):
        tid = self.current_trial_id()
        if tid is None:
            raise RuntimeError("tune.report() called outside a trial")
        metrics = dict(metrics)
        with self._lock:
            q = self._queues.get(tid)
            flag = self._stop_flags.get(tid)
            criteria = self._stop_criteria.get(tid, {})
            self._report_counts[tid] = self._report_counts.get(tid, 0) + 1
            metrics.setdefault("training_iteration", self._report_counts[tid])
        if q is not None:
            q.put({"metrics": metrics, "checkpoint": checkpoint})
        # run_config.stop criteria are enforced synchronously at the
        # report site so a free-running trial stops at exactly the bound
        # (the scheduler's early-stop decisions stay asynchronous).
        if any(k in metrics and metrics[k] >= bound
               for k, bound in criteria.items()):
            raise StopTrial()
        if flag is not None and flag.is_set():
            raise StopTrial()

    def get_checkpoint(self) -> Any:
        tid = self.current_trial_id()
        if tid is None:
            return None
        with self._lock:
            return self._restore.get(tid)


SESSION = _SessionChannel()


def report(metrics: Dict[str, Any], *, checkpoint: Any = None) -> None:
    """In-trial API (parity: ray.tune.report / session.report)."""
    SESSION.report(metrics, checkpoint)


def get_checkpoint() -> Any:
    """In-trial API (parity: session.get_checkpoint) — the checkpoint to
    resume from, if the trial was restored/exploited."""
    return SESSION.get_checkpoint()
