"""Tuner + TuneController: experiment execution over trial actors.

Parity with the reference's experiment runner (ray: python/ray/tune/
tuner.py:59 Tuner; tune/execution/tune_controller.py:81 — the event loop
that starts trial actors, consumes their results, applies scheduler
decisions, and retries/perturbs; trainable/trainable.py:76 for the class
Trainable API).  Trials run as actors on the core runtime; resources per
trial gate concurrency exactly like placement-group-backed trials do in
the reference.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.core.exceptions import TaskError
from ray_tpu.tune.schedulers import (
    CONTINUE,
    STOP,
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.trial import (
    ERROR,
    PENDING,
    RUNNING,
    SESSION,
    TERMINATED,
    StopTrial,
    Trial,
)


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Optional[TrialScheduler] = None
    seed: Optional[int] = None


@dataclasses.dataclass
class RunConfig:
    # Experiment persistence (parity: tune/execution/experiment_state.py
    # periodic driver snapshots + Tuner.restore).  storage_path=None
    # disables; else <storage_path>/<name>/experiment_state.pkl is
    # written atomically on a throttle and a killed-mid-sweep run can
    # be resumed with Tuner.restore(path, trainable).
    name: str = "experiment"
    stop: Optional[Dict[str, float]] = None  # e.g. {"training_iteration": 10}
    storage_path: Optional[str] = None
    snapshot_period_s: float = 1.0


@dataclasses.dataclass
class Result:
    config: Dict[str, Any]
    metrics: Optional[Dict[str, Any]]
    error: Optional[str]
    trial_id: str
    checkpoint: Any = None


class ResultGrid:
    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required")
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError("no trial reported the metric")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = {"trial_id": r.trial_id, "error": r.error}
            row.update({f"config/{k}": v for k, v in r.config.items()})
            if r.metrics:
                row.update(r.metrics)
            rows.append(row)
        return pd.DataFrame(rows)


class Trainable:
    """Class trainable API (parity: tune/trainable/trainable.py:76).
    Subclass with setup/step/save_checkpoint/load_checkpoint."""

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Any:
        return None

    def load_checkpoint(self, checkpoint: Any) -> None:
        pass


def with_resources(trainable, resources: Dict[str, float]):
    """Attach per-trial resources (parity: tune.with_resources)."""
    setattr(trainable, "__tune_resources__", dict(resources))
    return trainable


class _FnTrialRunner:
    """Actor wrapping a function trainable: runs it to completion on the
    actor's execution thread; reports buffer in the ACTOR-LOCAL session
    channel and the controller drains them via actor calls — so the
    same flow works whether the actor is a thread or its own OS worker
    process (parity: the controller fetching results from trainable
    actors rather than sharing memory with them)."""

    def run(self, trial_id: str, fn: Callable, config: Dict[str, Any],
            restore_checkpoint: Any = None,
            stop_criteria: Optional[Dict[str, float]] = None):
        SESSION.register(trial_id, restore_checkpoint, stop_criteria)
        SESSION.bind(trial_id)
        try:
            fn(config)
            return "DONE"
        except StopTrial:
            return "STOPPED"

    def drain(self, trial_id: str):
        return SESSION.drain(trial_id)

    def request_stop(self, trial_id: str):
        SESSION.request_stop(trial_id)

    def finish(self, trial_id: str):
        """Drop session state — load-bearing in thread mode, where the
        SESSION is the driver-global channel and would otherwise keep
        per-trial queues/checkpoints alive for the process lifetime."""
        SESSION.unregister(trial_id)


class _ClassTrialRunner:
    """Actor wrapping a class trainable: the controller drives step()."""

    def __init__(self, cls: type, config: Dict[str, Any]):
        self.obj = cls()
        self.obj.setup(dict(config))

    def step(self) -> Dict[str, Any]:
        return self.obj.step()

    def save(self) -> Any:
        return self.obj.save_checkpoint()

    def restore(self, checkpoint: Any) -> None:
        self.obj.load_checkpoint(checkpoint)


class TuneController:
    """The experiment event loop (parity: tune_controller.py:81)."""

    def __init__(self, trainable, param_space: Dict[str, Any],
                 tune_config: TuneConfig, run_config: RunConfig,
                 restored_trials: Optional[List[Trial]] = None):
        self.trainable = trainable
        self.param_space = param_space
        self.cfg = tune_config
        self.run_cfg = run_config
        self.scheduler = tune_config.scheduler or FIFOScheduler()
        self.is_class = isinstance(trainable, type) and issubclass(
            trainable, Trainable)
        self.resources = getattr(trainable, "__tune_resources__",
                                 {"CPU": 1.0})
        self._counter = itertools.count()
        self.trials: List[Trial] = []
        # trial_id -> pending exploit (source_checkpoint, new_config)
        self._exploits: Dict[str, Any] = {}
        self._restored = restored_trials
        self._exp_file: Optional[str] = None
        self._last_snapshot = 0.0
        if run_config.storage_path:
            import os

            d = os.path.join(run_config.storage_path, run_config.name)
            os.makedirs(d, exist_ok=True)
            self._exp_file = os.path.join(d, "experiment_state.pkl")

    # -- experiment persistence (parity: experiment_state.py) --------------

    def _maybe_snapshot(self, force: bool = False) -> None:
        if self._exp_file is None:
            return
        now = time.monotonic()
        if not force and now - self._last_snapshot < \
                self.run_cfg.snapshot_period_s:
            return
        self._last_snapshot = now
        import os
        import tempfile

        import cloudpickle as _cp

        rows = [
            {"trial_id": t.trial_id, "config": t.config,
             "status": t.status, "results": list(t.results),
             "error": t.error, "checkpoint": t.checkpoint}
            for t in self.trials
        ]
        blob = _cp.dumps({
            "version": 1,
            "trials": rows,
            "tune_config": self.cfg,
            "run_config": self.run_cfg,
            "param_space": self.param_space,
        })
        d = os.path.dirname(self._exp_file)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".exp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._exp_file)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- shared ------------------------------------------------------------

    def _make_trials(self):
        gen = BasicVariantGenerator(self.param_space,
                                    self.cfg.num_samples, self.cfg.seed)
        for config in gen:
            tid = f"trial_{next(self._counter):05d}"
            self.trials.append(Trial(trial_id=tid, config=config))

    def _hit_stop_criteria(self, result: Dict[str, Any]) -> bool:
        for key, bound in (self.run_cfg.stop or {}).items():
            if key in result and result[key] >= bound:
                return True
        return False

    def run(self) -> List[Trial]:
        if self._restored is not None:
            self.trials = self._restored
            # Warm the scheduler's rungs with the finished trials'
            # history (decisions from the replay are meaningless and
            # ignored — those trials won't run again).
            for t in self.trials:
                if t.status in (TERMINATED, ERROR):
                    for r in t.results:
                        try:
                            self.scheduler.on_result(t, r, self.trials)
                        except Exception:
                            pass
        else:
            self._make_trials()
        if self.is_class:
            self._run_class_trials()
        else:
            self._run_fn_trials()
        self._maybe_snapshot(force=True)
        return self.trials

    # -- function trainables ----------------------------------------------

    def _run_fn_trials(self):
        # max_concurrency=2: drain()/request_stop() must interleave with
        # the long-running run() on the same actor.
        Runner = ray_tpu.remote(
            max_concurrency=2, **_actor_opts(self.resources)
        )(_FnTrialRunner)
        active: List[Trial] = []
        # Resume skips already-finished trials (driver-crash restore).
        pending = [t for t in self.trials
                   if t.status not in (TERMINATED, ERROR)]
        fn = self.trainable
        while pending or active:
            while pending and len(active) < self.cfg.max_concurrent_trials:
                trial = pending.pop(0)
                self._start_fn_trial(trial, Runner, fn)
                active.append(trial)
            time.sleep(0.01)
            self._maybe_snapshot()
            for trial in list(active):
                self._pump_results(trial)
                done, _ = ray_tpu.wait([trial.run_ref], timeout=0)
                if done:
                    self._pump_results(trial)
                    self._finish_fn_trial(trial)
                    if trial.trial_id in self._exploits:
                        ckpt, cfg = self._exploits.pop(trial.trial_id)
                        trial.config = cfg
                        trial.restore_from = ckpt
                        self._start_fn_trial(trial, Runner, fn)
                    else:
                        active.remove(trial)

    def _start_fn_trial(self, trial: Trial, Runner, fn):
        trial.actor = Runner.remote()
        trial.status = RUNNING
        trial.run_ref = trial.actor.run.remote(
            trial.trial_id, fn, trial.config, trial.restore_from,
            self.run_cfg.stop,
        )

    def _finish_fn_trial(self, trial: Trial):
        try:
            ray_tpu.get(trial.run_ref)
            trial.status = TERMINATED
        except TaskError as e:
            trial.status = ERROR
            trial.error = str(e)
        finally:
            try:
                ray_tpu.get(trial.actor.finish.remote(trial.trial_id),
                            timeout=10)
            except Exception:
                pass  # dead actor: its session state died with it
            ray_tpu.kill(trial.actor)
            trial.actor = None

    def _pump_results(self, trial: Trial):
        if trial.actor is None:
            return
        try:
            items = ray_tpu.get(
                trial.actor.drain.remote(trial.trial_id), timeout=30
            )
        except Exception:
            return  # actor died mid-drain; _finish_fn_trial reports it
        for item in items:
            metrics = item["metrics"]
            metrics.setdefault("training_iteration", len(trial.results) + 1)
            trial.results.append(metrics)
            if item["checkpoint"] is not None:
                trial.checkpoint = item["checkpoint"]
            decision = self.scheduler.on_result(trial, metrics, self.trials)
            if self._hit_stop_criteria(metrics):
                decision = STOP
            if decision == STOP:
                trial.actor.request_stop.remote(trial.trial_id)
            elif decision == "EXPLOIT":
                target = self.scheduler.exploit_target(trial, self.trials)
                if target is not None:
                    source, new_config = target
                    self._exploits[trial.trial_id] = (
                        source.checkpoint, new_config)
                    trial.actor.request_stop.remote(trial.trial_id)

    # -- class trainables --------------------------------------------------

    def _run_class_trials(self):
        Runner = ray_tpu.remote(**_actor_opts(self.resources))(
            _ClassTrialRunner)
        active: List[Trial] = []
        pending = [t for t in self.trials
                   if t.status not in (TERMINATED, ERROR)]
        step_refs: Dict[str, Any] = {}
        while pending or active:
            while pending and len(active) < self.cfg.max_concurrent_trials:
                trial = pending.pop(0)
                trial.actor = Runner.remote(self.trainable, trial.config)
                trial.status = RUNNING
                if trial.restore_from is not None:
                    # Driver-crash resume: rebuild the trainable from
                    # the trial's last checkpoint.
                    ray_tpu.get(trial.actor.restore.remote(
                        trial.restore_from))
                    trial.restore_from = None
                step_refs[trial.trial_id] = trial.actor.step.remote()
                active.append(trial)
            time.sleep(0.005)
            self._maybe_snapshot()
            for trial in list(active):
                ref = step_refs.get(trial.trial_id)
                done, _ = ray_tpu.wait([ref], timeout=0)
                if not done:
                    continue
                try:
                    metrics = ray_tpu.get(ref)
                except TaskError as e:
                    trial.status = ERROR
                    trial.error = str(e)
                    ray_tpu.kill(trial.actor)
                    active.remove(trial)
                    step_refs.pop(trial.trial_id, None)
                    continue
                metrics.setdefault("training_iteration",
                                   len(trial.results) + 1)
                trial.results.append(metrics)
                trial.checkpoint = ray_tpu.get(trial.actor.save.remote())
                decision = self.scheduler.on_result(trial, metrics,
                                                    self.trials)
                if self._hit_stop_criteria(metrics):
                    decision = STOP
                if decision == "EXPLOIT":
                    target = self.scheduler.exploit_target(trial, self.trials)
                    if target is not None:
                        source, new_config = target
                        ray_tpu.kill(trial.actor)
                        trial.config = new_config
                        trial.actor = Runner.remote(self.trainable,
                                                    new_config)
                        if source.checkpoint is not None:
                            ray_tpu.get(trial.actor.restore.remote(
                                source.checkpoint))
                        step_refs[trial.trial_id] = \
                            trial.actor.step.remote()
                        continue
                    decision = CONTINUE
                if decision == STOP:
                    trial.status = TERMINATED
                    ray_tpu.kill(trial.actor)
                    active.remove(trial)
                    step_refs.pop(trial.trial_id, None)
                else:
                    step_refs[trial.trial_id] = trial.actor.step.remote()


def _actor_opts(resources: Dict[str, float]) -> Dict[str, Any]:
    opts: Dict[str, Any] = {}
    res = dict(resources)
    opts["num_cpus"] = float(res.pop("CPU", 1.0))
    if "TPU" in res:
        opts["num_tpus"] = float(res.pop("TPU"))
    if res:
        opts["resources"] = res
    return opts


class Tuner:
    """Public entry (parity: tune/tuner.py:59)."""

    def __init__(self, trainable, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    @classmethod
    def restore(cls, path: str, trainable) -> "Tuner":
        """Rebuild a Tuner from a periodic experiment snapshot so a
        sweep survives a DRIVER crash (parity:
        tune/execution/experiment_state.py + Tuner.restore): finished
        trials keep their results; interrupted trials resume from their
        last reported checkpoint; never-started trials run normally.
        ``path`` is <storage_path>/<name> or the experiment_state.pkl
        itself."""
        import os

        import cloudpickle as _cp

        from ray_tpu.tune.trial import Trial as _Trial

        f = (path if path.endswith(".pkl")
             else os.path.join(path, "experiment_state.pkl"))
        with open(f, "rb") as fh:
            snap = _cp.loads(fh.read())
        trials = []
        for row in snap["trials"]:
            t = _Trial(trial_id=row["trial_id"], config=row["config"],
                       status=row["status"], results=row["results"],
                       error=row["error"], checkpoint=row["checkpoint"])
            if t.status not in (TERMINATED, ERROR):
                # Interrupted mid-run: restart from the newest
                # checkpoint (or from scratch if none reported yet).
                t.status = PENDING
                t.restore_from = t.checkpoint
                t.results = list(t.results)
            trials.append(t)
        tuner = cls(trainable, param_space=snap["param_space"],
                    tune_config=snap["tune_config"],
                    run_config=snap["run_config"])
        tuner._restored_trials = trials
        return tuner

    def fit(self) -> ResultGrid:
        controller = TuneController(
            self.trainable, self.param_space, self.tune_config,
            self.run_config,
            restored_trials=getattr(self, "_restored_trials", None),
        )
        trials = controller.run()
        results = [
            Result(config=t.config, metrics=t.last_result(), error=t.error,
                   trial_id=t.trial_id, checkpoint=t.checkpoint)
            for t in trials
        ]
        return ResultGrid(results, self.tune_config.metric,
                          self.tune_config.mode)


def run(trainable, *, param_space: Optional[Dict] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler: Optional[TrialScheduler] = None,
        stop: Optional[Dict[str, float]] = None,
        max_concurrent_trials: int = 4) -> ResultGrid:
    """Functional entry (parity: tune.run, tune/tune.py:293)."""
    return Tuner(
        trainable,
        param_space=param_space,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples,
                               scheduler=scheduler,
                               max_concurrent_trials=max_concurrent_trials),
        run_config=RunConfig(stop=stop),
    ).fit()
