"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Parity with the reference's scheduler suite (ray: python/ray/tune/
schedulers/ — async_hyperband.py AsyncHyperBandScheduler,
median_stopping_rule.py, pbt.py PopulationBasedTraining).  Decisions are
made per reported result: CONTINUE, STOP, or (PBT) EXPLOIT with a new
config + a source checkpoint.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial: Trial, result: Dict[str, Any],
                  all_trials: List[Trial]) -> str:
        return CONTINUE

    def exploit_target(self, trial: Trial, all_trials: List[Trial]
                       ) -> Optional[Tuple[Trial, Dict[str, Any]]]:
        """PBT hook: (source_trial, new_config) or None."""
        return None


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (parity: schedulers/async_hyperband.py): successive-halving
    brackets checked asynchronously at rung boundaries — a trial stops at
    a rung if its metric is below the top 1/reduction_factor quantile of
    completed rung entries."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung level -> list of recorded metric values
        self.rungs: Dict[int, List[float]] = {}
        rung = grace_period
        self.rung_levels = []
        while rung < max_t:
            self.rung_levels.append(rung)
            rung = int(rung * self.rf)

    def on_result(self, trial, result, all_trials) -> str:
        t = result.get(self.time_attr)
        v = result.get(self.metric)
        if t is None or v is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for level in self.rung_levels:
            if t == level:
                recorded = self.rungs.setdefault(level, [])
                recorded.append(float(v))
                if len(recorded) < self.rf:
                    return CONTINUE  # not enough evidence yet
                # Keep the top 1/rf quantile (percentile cutoff, matching
                # the reference's _Bracket.cutoff).
                import numpy as np

                if self.mode == "max":
                    cutoff = float(np.percentile(
                        recorded, 100 * (1 - 1 / self.rf)))
                    good = v >= cutoff
                else:
                    cutoff = float(np.percentile(recorded, 100 / self.rf))
                    good = v <= cutoff
                return CONTINUE if good else STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running best is worse than the median of other
    trials' running bests at the same step
    (parity: schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required

    def on_result(self, trial, result, all_trials) -> str:
        t = result.get(self.time_attr)
        if t is None or t < self.grace_period:
            return CONTINUE
        others = []
        for other in all_trials:
            if other.trial_id == trial.trial_id:
                continue
            best = other.best_metric(self.metric, self.mode)
            if best is not None:
                others.append(best)
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        mine = trial.best_metric(self.metric, self.mode)
        if mine is None:
            return CONTINUE
        bad = mine < median if self.mode == "max" else mine > median
        return STOP if bad else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (parity: schedulers/pbt.py): at each perturbation interval,
    bottom-quantile trials EXPLOIT a top-quantile trial's checkpoint and
    EXPLORE a mutated config."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)

    def _quantiles(self, all_trials: List[Trial]):
        scored = [(t.best_metric(self.metric, self.mode), t)
                  for t in all_trials]
        scored = [(s, t) for s, t in scored if s is not None]
        if len(scored) < 2:
            return [], []
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        top = [t for _, t in scored[:k]]
        bottom = [t for _, t in scored[-k:]]
        return top, bottom

    def on_result(self, trial, result, all_trials) -> str:
        t = result.get(self.time_attr)
        if t is None or t % self.interval != 0:
            return CONTINUE
        top, bottom = self._quantiles(all_trials)
        if trial in bottom and trial not in top:
            return "EXPLOIT"
        return CONTINUE

    def exploit_target(self, trial, all_trials):
        top, _ = self._quantiles(all_trials)
        top = [t for t in top if t.trial_id != trial.trial_id]
        if not top:
            return None
        source = self.rng.choice(top)
        new_config = self._explore(dict(source.config))
        return source, new_config

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain

        for key, spec in self.mutations.items():
            if isinstance(spec, list):
                config[key] = self.rng.choice(spec)
            elif isinstance(spec, Domain):
                config[key] = spec.sample(self.rng)
            elif callable(spec):
                config[key] = spec()
            elif key in config and isinstance(config[key], (int, float)):
                factor = self.rng.choice([0.8, 1.2])
                config[key] = config[key] * factor
        return config
