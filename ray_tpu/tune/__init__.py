"""ray_tpu.tune — experiment runner (parity: python/ray/tune;
see SURVEY.md §2.3)."""

from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.trial import Trial, get_checkpoint, report
from ray_tpu.tune.tuner import (
    Result,
    ResultGrid,
    RunConfig,
    Trainable,
    TuneConfig,
    TuneController,
    Tuner,
    run,
    with_resources,
)

__all__ = [
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "Result",
    "ResultGrid",
    "RunConfig",
    "Trainable",
    "Trial",
    "TrialScheduler",
    "TuneConfig",
    "TuneController",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "loguniform",
    "quniform",
    "randint",
    "report",
    "run",
    "sample_from",
    "uniform",
    "with_resources",
]
