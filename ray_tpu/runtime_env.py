"""Runtime environments: per-task/actor execution environments.

Parity: the reference's runtime-env system (ray:
python/ray/runtime_env/runtime_env.py RuntimeEnv; plugins under
python/ray/_private/runtime_env/{working_dir,py_modules,pip,conda,
plugin}.py; URI-addressed package cache in
_private/runtime_env/packaging.py; design doc
python/ray/runtime_env/ARCHITECTURE.md).

Supported fields:
  env_vars     dict[str,str] — applied around execution
  working_dir  path or pkg URI — packaged (zip, content-hash URI),
               cached, extracted, prepended to sys.path and exported as
               RAYTPU_WORKING_DIR
  py_modules   list of paths/URIs — packaged like working_dir, each
               extracted and importable
  config       {"setup_timeout_seconds": ...} accepted for parity
  pip          list of requirements (or {"packages": [...]}) — built
               ONCE per requirement-set hash with ``pip install
               --target`` into the shared cache, then prepended to
               sys.path (parity: _private/runtime_env/pip.py's
               hash-keyed virtualenv builds).  Local wheel paths work
               offline; index installs need egress.
  conda        rejected: this build disallows conda environments
               (the reference shells out to conda in the agent)

Worker model note: the reference materializes envs per worker
*process*; this runtime executes tasks on threads, so env_vars /
sys.path application is process-global and serialized under a lock —
same observable semantics for the common one-env-at-a-time case,
honest-best-effort under concurrency (documented, like the reference's
per-process limitation that envs cannot change within a worker).
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import shutil
import sys
import tempfile
import threading
import zipfile
from typing import Any, Dict, List, Optional

_KNOWN_FIELDS = {"env_vars", "working_dir", "py_modules", "config",
                 "pip", "conda"}

_PKG_SCHEME = "pkg://"


class RuntimeEnv(dict):
    """Validated runtime-env spec (parity: ray.runtime_env.RuntimeEnv —
    a dict subclass with field validation)."""

    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 config: Optional[Dict[str, Any]] = None,
                 **kwargs):
        super().__init__()
        unknown = set(kwargs) - _KNOWN_FIELDS
        if unknown:
            raise ValueError(
                f"unknown runtime_env field(s) {sorted(unknown)}; "
                f"known: {sorted(_KNOWN_FIELDS)}"
            )
        if "conda" in kwargs:
            raise NotImplementedError(
                "conda runtime envs are disabled in this build; use "
                "pip requirements or bake dependencies into the image"
            )
        pip = kwargs.pop("pip", None)
        if pip is not None:
            if isinstance(pip, dict):
                pip = pip.get("packages", [])
            if not isinstance(pip, (list, tuple)) or not all(
                isinstance(r, str) for r in pip
            ):
                raise TypeError("pip must be a list of requirement strings")
            self["pip"] = list(pip)
        if env_vars:
            for k, v in env_vars.items():
                if not isinstance(k, str) or not isinstance(v, str):
                    raise TypeError("env_vars must be str → str")
            self["env_vars"] = dict(env_vars)
        if working_dir:
            self["working_dir"] = working_dir
        if py_modules:
            self["py_modules"] = list(py_modules)
        if config:
            self["config"] = dict(config)
        for k, v in kwargs.items():  # registered plugin fields
            self[k] = v

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "RuntimeEnv":
        return cls(**(d or {}))


# -- packaging: content-addressed zips (parity: packaging.py) --------------

def _cache_dir() -> str:
    d = os.path.join(tempfile.gettempdir(), "raytpu-runtime-env-cache")
    os.makedirs(d, exist_ok=True)
    return d


def package_directory(path: str) -> str:
    """Zip a directory into the cache, named by content hash; returns a
    ``pkg://<hash>.zip`` URI (parity: get_uri_for_directory +
    upload_package_if_needed — the GCS upload hop collapses to the
    shared cache dir)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"working_dir/py_module {path!r} is not a directory")
    h = hashlib.sha256()
    entries = []
    for root, _dirs, files in os.walk(path):
        for f in sorted(files):
            full = os.path.join(root, f)
            rel = os.path.relpath(full, path)
            entries.append((full, rel))
    for full, rel in sorted(entries, key=lambda e: e[1]):
        h.update(rel.encode())
        with open(full, "rb") as fh:
            h.update(fh.read())
    digest = h.hexdigest()[:32]
    zip_path = os.path.join(_cache_dir(), f"{digest}.zip")
    if not os.path.exists(zip_path):
        tmp = zip_path + ".tmp"
        with zipfile.ZipFile(tmp, "w") as z:
            for full, rel in entries:
                z.write(full, rel)
        os.replace(tmp, zip_path)
    return f"{_PKG_SCHEME}{digest}.zip"


def ensure_local(uri: str) -> str:
    """Extract a package URI into the cache (idempotent); returns the
    local directory (parity: download_and_unpack_package with the
    per-URI local cache)."""
    if not uri.startswith(_PKG_SCHEME):
        raise ValueError(f"not a package URI: {uri!r}")
    name = uri[len(_PKG_SCHEME):]
    zip_path = os.path.join(_cache_dir(), name)
    out_dir = os.path.join(_cache_dir(), name[:-len(".zip")])
    if not os.path.isdir(out_dir):
        # Extract into a UNIQUE temp dir, then atomically install: two
        # concurrent extractors (threads or worker processes) each get
        # their own staging dir, so neither can rmtree the other
        # mid-extract; the loser of os.replace just discards its copy.
        tmp = tempfile.mkdtemp(prefix=name + ".", dir=_cache_dir())
        try:
            with zipfile.ZipFile(zip_path) as z:
                for member in z.namelist():
                    # Zip-slip guard: refuse absolute paths and ".."
                    # escapes from cache-resident archives.
                    p = os.path.normpath(member)
                    if p == ".." or p.startswith("../") or os.path.isabs(p):
                        raise ValueError(
                            f"unsafe path in package {name!r}: {member!r}")
                z.extractall(tmp)
            try:
                os.replace(tmp, out_dir)
            except OSError:
                if not os.path.isdir(out_dir):  # lost a benign race?
                    raise
                shutil.rmtree(tmp, ignore_errors=True)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
    return out_dir


# -- pip environments (parity: _private/runtime_env/pip.py) ----------------

def ensure_pip(requirements: List[str], timeout_s: float = 600.0) -> str:
    """Build (once) and return the ``pip install --target`` site dir
    for a requirement set, keyed by the sorted-requirements hash
    (parity: pip.py's hash-named virtualenv under the resources dir,
    built by the per-node agent and reused across workers).  Concurrent
    builders race on an O_EXCL lock file; losers wait for the winner's
    .done marker."""
    import subprocess
    import time as _time

    reqs = sorted(requirements)
    key = hashlib.sha256("\n".join(reqs).encode()).hexdigest()[:32]
    target = os.path.join(_cache_dir(), f"pip-{key}")
    done = target + ".done"
    lock = target + ".lock"
    deadline = _time.monotonic() + timeout_s
    while True:
        if os.path.exists(done):
            return target
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            # Another builder holds the lock.  A builder that DIED
            # (SIGKILL mid-install) leaves a stale lock forever — treat
            # a sufficiently old lock as abandoned and break it, then
            # retry the claim; a live builder refreshes nothing, but
            # its install finishing shows up as the .done marker.
            try:
                age = _time.time() - os.path.getmtime(lock)
            except OSError:
                continue  # lock vanished — retry claim immediately
            if age > 60.0:
                try:
                    os.unlink(lock)
                except OSError:
                    pass
                continue
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"pip env {key} build did not finish in {timeout_s}s"
                )
            _time.sleep(0.2)
            continue
        os.close(fd)
        break
    try:
        if os.path.exists(done):
            return target
        tmp = target + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        heartbeat = _Heartbeat(lock)
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "pip", "install", "--target", tmp,
                 "--no-input", "--disable-pip-version-check",
                 "--no-warn-script-location", *reqs],
                capture_output=True, text=True, timeout=timeout_s,
            )
        finally:
            heartbeat.stop()
        if proc.returncode != 0:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"pip install failed for {reqs}: "
                f"{proc.stderr.strip()[-800:]}"
            )
        os.replace(tmp, target)
        with open(done, "w") as f:
            f.write("\n".join(reqs))
        return target
    finally:
        try:
            os.unlink(lock)
        except OSError:
            pass


class _Heartbeat:
    """Touches a lock file periodically so waiters can tell a live
    long-running build from an abandoned one (mtime-based staleness)."""

    def __init__(self, path: str, period_s: float = 15.0):
        import threading as _threading

        self._path = path
        self._stop = _threading.Event()

        def beat():
            while not self._stop.wait(period_s):
                try:
                    os.utime(self._path)
                except OSError:
                    return

        self._thread = _threading.Thread(target=beat, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


# -- plugins (parity: _private/runtime_env/plugin.py) ----------------------

class RuntimeEnvPlugin:
    """Extension point: a named field handled by user code."""

    name: str = ""
    priority: int = 10

    def create(self, value: Any, ctx: "RuntimeEnvContext") -> None:
        raise NotImplementedError


_plugins: Dict[str, RuntimeEnvPlugin] = {}
_plugins_version = 0  # bumped on registration; invalidates ship caches


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    global _plugins_version
    if not plugin.name:
        raise ValueError("plugin needs a name")
    _plugins[plugin.name] = plugin
    _KNOWN_FIELDS.add(plugin.name)
    _plugins_version += 1


# -- materialization -------------------------------------------------------

# Serializes process-global mutation (os.environ, sys.path) across
# concurrently executing tasks — see module docstring.
_apply_lock = threading.RLock()


class RuntimeEnvContext:
    """Materialized env for one execution (parity:
    _private/runtime_env/context.py RuntimeEnvContext)."""

    def __init__(self, env: RuntimeEnv):
        self.env = env
        self.env_vars: Dict[str, str] = dict(env.get("env_vars", {}))
        self.sys_paths: List[str] = []

    def build(self) -> "RuntimeEnvContext":
        wd = self.env.get("working_dir")
        if wd:
            uri = wd if wd.startswith(_PKG_SCHEME) else package_directory(wd)
            local = ensure_local(uri)
            self.sys_paths.append(local)
            self.env_vars["RAYTPU_WORKING_DIR"] = local
        for mod in self.env.get("py_modules", []):
            uri = (mod if mod.startswith(_PKG_SCHEME)
                   else package_directory(mod))
            self.sys_paths.append(ensure_local(uri))
        pip_reqs = self.env.get("pip")
        if pip_reqs:
            cfg = self.env.get("config") or {}
            self.sys_paths.append(ensure_pip(
                pip_reqs,
                timeout_s=float(cfg.get("setup_timeout_seconds", 600)),
            ))
        for name, plugin in sorted(_plugins.items(),
                                   key=lambda kv: kv[1].priority):
            if name in self.env:
                plugin.create(self.env[name], self)
        return self

    @contextlib.contextmanager
    def applied(self):
        """Apply env vars + sys.path for the duration of one task.

        The lock guards only the mutate/restore critical sections, NOT
        the task body — holding it across execution would deadlock any
        env'd task that blocks on another env'd task.  Concurrent tasks
        with different envs may therefore observe each other's vars
        (best-effort under threads; the reference's per-process workers
        have true isolation)."""
        with _apply_lock:
            saved_env = {k: os.environ.get(k) for k in self.env_vars}
            os.environ.update(self.env_vars)
            saved_paths = list(self.sys_paths)
            for p in reversed(saved_paths):
                sys.path.insert(0, p)
        try:
            yield self
        finally:
            with _apply_lock:
                for k, old in saved_env.items():
                    if old is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = old
                for p in saved_paths:
                    try:
                        sys.path.remove(p)
                    except ValueError:
                        pass


def materialize(spec) -> Optional[RuntimeEnvContext]:
    """spec: None | dict | RuntimeEnv → built context (or None)."""
    if not spec:
        return None
    env = spec if isinstance(spec, RuntimeEnv) else RuntimeEnv.from_dict(spec)
    return RuntimeEnvContext(env).build()
