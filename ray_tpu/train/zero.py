"""Cross-replica sharding of the weight update (ZeRO-style).

Implements the layout side of "Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training" (PAPERS.md, arXiv 2004.13336):
Adam's mu/nu (and the fp32 mirror of the fused update) live sharded
over the data axes instead of replicated per dp member.  With the
optimizer state's out_shardings pinned here, the GSPMD partitioner
converts the gradient all-reduce into reduce-scatter → local update on
1/dp of the blocks → all-gather of the updated params — no explicit
collectives in the step function (the in-update sharding constraints in
train/optim8.py are the escape hatch that keeps the partitioner honest
on the int8 blockwise path).

Memory math this buys: int8 Adam states cost ~2 B/param replicated
(train/optim8.py); sharded they cost ~2/dp B/param per device, which is
what lets full-8B AdamW train on a slice where the replicated states
alone would blow HBM.

Layout rules, per optimizer-state subtree of a ``TrainState``:

* param-mirror subtrees (fp32/bf16 mu/nu with the params' structure)
  keep their param logical axes and additionally shard their largest
  still-replicated dim over the free data axes when sizes divide;
* int8 blockwise subtrees (optim8's ``(q [nb, 256], scale [nb, 1])``
  leaves) shard the leading block dim — the natural ZeRO shard dim;
* scalars (counts, schedule state) replicate.

Sharding never pads: a dim is sharded over the longest prefix of the
data axes whose size product divides it (XLA rejects uneven
in/out shardings), so tiny leaves (norms, biases) stay replicated and
all the bytes that matter — the big matmul weights — shard fully.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import Rules, spec_for
from ray_tpu.train.state import TrainState, _is_axes_leaf

# Logical axis name the rule table maps to the weight-update shard axes
# (DEFAULT_RULES: ("dp", "fsdp"), DCN-expanded on hybrid meshes).
ZERO_AXIS = "zero"


def zero_axes(mesh, rules: Optional[Rules] = None) -> Tuple[str, ...]:
    """Mesh axes the weight update shards over: the "zero" rule resolved
    against ``mesh``, keeping only axes actually present with size > 1."""
    spec = spec_for((ZERO_AXIS,), rules,
                    mesh_axes=frozenset(mesh.axis_names))
    entry = spec[0] if len(spec) else None
    if entry is None:
        return ()
    axes = (entry,) if isinstance(entry, str) else tuple(entry)
    return tuple(a for a in axes if mesh.shape.get(a, 1) > 1)


def dp_shards(mesh, rules: Optional[Rules] = None) -> int:
    """How many ways the optimizer state shards (1 = replicated layout)."""
    return max(1, math.prod(mesh.shape[a] for a in zero_axes(mesh, rules)))


def shardable_prefix(size: int, axes: Tuple[str, ...], mesh
                     ) -> Tuple[str, ...]:
    """Longest prefix of ``axes`` whose size product divides ``size``."""
    for k in range(len(axes), 0, -1):
        prefix = axes[:k]
        if size % math.prod(mesh.shape.get(a, 1) for a in prefix) == 0:
            return prefix
    return ()


def _axis_tuple(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    return (entry,) if isinstance(entry, str) else tuple(entry)


def _is_blockpair(node) -> bool:
    """optim8's (q int8 [nb, BLOCK], f32 scale [nb, 1]) leaf pair."""
    if not (isinstance(node, tuple) and not hasattr(node, "_fields")
            and len(node) == 2):
        return False
    q, s = node
    return (getattr(q, "ndim", 0) == 2 and getattr(s, "ndim", 0) == 2
            and str(getattr(q, "dtype", "")) == "int8"
            and tuple(s.shape) == (q.shape[0], 1))


def block_sharding(mesh, shape: Tuple[int, ...],
                   rules: Optional[Rules] = None) -> NamedSharding:
    """Sharding for a blockwise buffer: leading (block) dim over the
    data axes, divisibility permitting; replicated otherwise."""
    ax = shardable_prefix(shape[0], zero_axes(mesh, rules), mesh) \
        if shape else ()
    if not ax:
        return NamedSharding(mesh, P())
    entry = ax[0] if len(ax) == 1 else ax
    return NamedSharding(mesh, P(entry, *([None] * (len(shape) - 1))))


def _extend_spec(entries, shape, free: Tuple[str, ...], mesh):
    """Assign the free data axes to the largest effectively-replicated
    dim they divide.  A dim already annotated with size-1 axes counts as
    replicated — the free axes compose onto it (sub-axis sharding), so
    e.g. a ("vocab", "embed") mirror still ZeRO-shards on a pure-dp
    mesh where vocab→tp and embed→fsdp are both trivial."""
    for d in sorted(range(len(shape)), key=lambda d: -shape[d]):
        cur = _axis_tuple(entries[d])
        if math.prod(mesh.shape.get(a, 1) for a in cur) != 1:
            continue
        usable = shardable_prefix(shape[d], free, mesh)
        if not usable:
            continue
        new = cur + usable
        entries[d] = new[0] if len(new) == 1 else new
        return entries
    return entries


def zero_state_shardings(mesh, state: TrainState, params_axes: Any,
                         rules: Optional[Rules] = None) -> TrainState:
    """ZeRO layout for a whole ``TrainState``: params keep their logical
    axes; optimizer state additionally shards over the data axes."""
    mesh_axes = frozenset(mesh.axis_names)
    flat_axes = jax.tree.leaves(params_axes, is_leaf=_is_axes_leaf)
    params_struct = jax.tree.structure(state.params)
    param_sh = jax.tree.unflatten(
        params_struct,
        [NamedSharding(mesh, spec_for(a, rules, mesh_axes=mesh_axes))
         for a in flat_axes])
    zaxes = zero_axes(mesh, rules)

    def mirror(axes, leaf) -> NamedSharding:
        spec = spec_for(axes, rules, mesh_axes=mesh_axes)
        entries = list(spec) + [None] * (leaf.ndim - len(spec))
        used = {a for e in entries for a in _axis_tuple(e)}
        free = tuple(a for a in zaxes if a not in used)
        if free:
            entries = _extend_spec(entries, leaf.shape, free, mesh)
        return NamedSharding(mesh, P(*entries))

    def rec(node):
        if jax.tree.structure(node) == params_struct:
            leaves = params_struct.flatten_up_to(node)
            return jax.tree.unflatten(
                params_struct,
                [mirror(a, l) for a, l in zip(flat_axes, leaves)])
        try:
            sub = params_struct.flatten_up_to(node)
        except Exception:
            sub = None
        if sub is not None and all(_is_blockpair(x) for x in sub):
            return jax.tree.unflatten(
                params_struct,
                [(block_sharding(mesh, tuple(q.shape), rules),
                  block_sharding(mesh, tuple(s.shape), rules))
                 for q, s in sub])
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*[rec(v) for v in node])
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v) for v in node)
        return NamedSharding(mesh, P())

    return TrainState(
        step=NamedSharding(mesh, P()),
        params=param_sh,
        opt_state=rec(state.opt_state),
    )


def opt_state_bytes(opt_state: Any) -> dict:
    """Optimizer-state footprint from the arrays' actual shardings:
    ``global`` bytes across the mesh and ``per_device`` bytes resident
    on one device (~global/dp under ZeRO, == global replicated)."""
    g = per = 0
    for leaf in jax.tree.leaves(opt_state):
        dtype = getattr(leaf, "dtype", None)
        shape = getattr(leaf, "shape", None)
        if dtype is None or shape is None:
            continue
        itemsize = jnp.dtype(dtype).itemsize
        g += math.prod(shape) * itemsize
        sh = getattr(leaf, "sharding", None)
        local = (math.prod(sh.shard_shape(tuple(shape)))
                 if sh is not None else math.prod(shape))
        per += local * itemsize
    return {"global": g, "per_device": per}
