"""Per-worker training session: report() + get_context().

Parity: ray: python/ray/train/_internal/session.py — ``_TrainSession``
(:132) bound per worker, ``report(metrics, checkpoint)`` (:612,844)
streaming results to the driver, and the public context surface
(train.get_context(): rank / world size / local rank).  The session is
thread-local: each worker actor's execution thread binds one.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional

_tls = threading.local()

_TELEMETRY = None


def _telemetry():
    """Session metric singleton (re-registered on refetch — see
    serve/llm_engine._telemetry for the registry-clear rationale)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "reports": metrics.Counter(
                "raytpu_train_reports_total",
                "train.report() calls streamed to the driver, by rank.",
                tag_keys=("rank",),
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


@dataclasses.dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str = ""

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank


class _Session:
    def __init__(self, context: TrainContext, report_fn):
        self.context = context
        self.report_fn = report_fn
        self.latest_checkpoint: Optional[Any] = None


def init_session(context: TrainContext, report_fn,
                 latest_checkpoint: Optional[Any] = None) -> None:
    s = _Session(context, report_fn)
    s.latest_checkpoint = latest_checkpoint
    _tls.session = s


def shutdown_session() -> None:
    _tls.session = None


def _get_session() -> _Session:
    s = getattr(_tls, "session", None)
    if s is None:
        raise RuntimeError(
            "no train session on this thread — report()/get_context() "
            "are only valid inside a train_loop_per_worker"
        )
    return s


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Any] = None) -> None:
    """Stream metrics (and optionally a checkpoint payload) to the
    driver (parity: ray.train.report)."""
    s = _get_session()
    _telemetry()["reports"].inc(
        tags={"rank": str(s.context.world_rank)})
    s.report_fn(dict(metrics), checkpoint)


def get_context() -> TrainContext:
    return _get_session().context


def get_checkpoint() -> Optional[Any]:
    """The checkpoint to resume from, if the trainer was restored
    (parity: train.get_checkpoint)."""
    return _get_session().latest_checkpoint
