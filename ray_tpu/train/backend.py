"""Training backends: wiring a real multi-process jax world.

Parity: the reference's Backend/BackendConfig abstraction (ray:
python/ray/train/backend.py:15,27) whose torch instance builds the NCCL
process group from worker-0's rendezvous address
(train/torch/config.py:63 _setup_torch_process_group).  The TPU-native
instance instead calls ``jax.distributed.initialize`` in EVERY worker
process — after which ``jax.devices()`` is the global device set and
pjit/shard_map programs emit cross-process collectives (XLA over
ICI/DCN on TPU pods; gloo on the CPU backend used in tests).

SPMD-vs-actor impedance (SURVEY.md §7 hard part 5): one worker actor is
pinned per host, all enter the same program, and a worker restart means
the whole world re-forms — DataParallelTrainer's retry tears the group
down (killing every worker PROCESS, which dissolves the old world) and
the next attempt builds a fresh one on a fresh coordinator, resuming
from the latest checkpoint.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class JaxBackendConfig:
    """Parity: BackendConfig (train/backend.py:15)."""

    # Force a platform in the workers ("cpu" for multi-process CPU
    # worlds in tests; None = let jax pick, i.e. TPU when present).
    platform: Optional[str] = None
    # 0 = pick a free port on worker 0's host.
    coordinator_port: int = 0
    # Pass through to jax.distributed.initialize (e.g. 4 chips/host).
    local_device_ids: Optional[List[int]] = None
    # Virtual CPU devices per process (cpu platform only): >1 models a
    # multi-chip host, so a 2-process world exercises the same
    # process-boundary SPMD as a 2-host × N-chip pod.
    cpu_devices_per_process: int = 1


# Module-level worker functions: shipped by reference, run inside the
# worker processes.

def _pick_coordinator(port: int) -> str:
    import socket

    host = socket.gethostbyname(socket.gethostname())
    if port == 0:
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
    return f"{host}:{port}"


def _init_jax_distributed(addr: str, num_processes: int, process_id: int,
                          platform: Optional[str],
                          local_device_ids: Optional[List[int]],
                          cpu_devices_per_process: int = 1) -> int:
    """Runs in the worker process BEFORE any other jax backend use —
    fresh worker processes import jax lazily, so the train fn sees the
    initialized world (parity: process-group init before the loop)."""
    import os
    import re

    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    if platform == "cpu":
        # Pin LOCAL device count per process: a test driver's inherited
        # --xla_force_host_platform_device_count=8 would otherwise give
        # every process 8 virtual devices and a world of 8N.
        flags = os.environ.get("XLA_FLAGS", "")
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       flags)
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{max(1, cpu_devices_per_process)}"
        ).strip()
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        # Cross-process computations on the CPU backend need a real
        # collectives implementation behind the PjRt client (jax's
        # default is "none", which refuses multiprocess programs).
        # Gloo over TCP is the CPU stand-in for the ICI/DCN fabric.
        # config.update (not env) so it also lands when the worker
        # process inherited an already-imported jax from its parent.
        jax.config.update(
            "jax_cpu_collectives_implementation",
            os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION",
                           "gloo"))
    kwargs: Dict[str, Any] = {}
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    return len(jax.devices())


def _shutdown_jax_distributed() -> None:
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass


class JaxDistributedBackend:
    """Forms the jax world across a WorkerGroup (parity: Backend —
    on_start builds the process group, on_shutdown destroys it)."""

    def __init__(self, config: Optional[JaxBackendConfig] = None):
        self.config = config or JaxBackendConfig()
        self.coordinator_address: Optional[str] = None

    def on_start(self, worker_group) -> List[int]:
        """Initialize every worker's jax.distributed; returns each
        worker's global device count (all equal once formed)."""
        import ray_tpu

        cfg = self.config
        self.coordinator_address = worker_group.execute_single(
            0, _pick_coordinator, cfg.coordinator_port
        )
        n = worker_group.num_workers
        # All initialize calls must be in flight together — each blocks
        # until the full world connects to the coordinator.
        refs = [
            w.execute.remote(
                _init_jax_distributed, self.coordinator_address, n, rank,
                cfg.platform, cfg.local_device_ids,
                cfg.cpu_devices_per_process,
            )
            for rank, w in enumerate(worker_group.workers)
        ]
        return ray_tpu.get(refs, timeout=120)

    def on_shutdown(self, worker_group) -> None:
        import ray_tpu

        try:
            ray_tpu.get(
                [w.execute.remote(_shutdown_jax_distributed)
                 for w in worker_group.workers],
                timeout=10,
            )
        except Exception:
            pass  # dying workers take their world down with them
