"""Actor worker group + backend executor for multi-worker training.

Parity: ray: python/ray/train/_internal/worker_group.py:101
(``WorkerGroup`` — N actors, execute on all / on one) and
backend_executor.py:46 (``BackendExecutor`` — start:105 creates the
group in a placement group, wires ranks and the rendezvous env, then
start_training:344 launches the user loop per worker with a session).

TPU mapping (SURVEY.md §7 hard part 5): one worker per TPU host, all
entering the same SPMD program — the backend sets the
``jax.distributed`` rendezvous env (coordinator address, process id,
process count) instead of NCCL's MASTER_ADDR.  In the single-process
runtime those env vars parameterize the worker's context; on a real
pod each worker actor would call ``jax.distributed.initialize`` with
them.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.session import TrainContext, init_session, \
    shutdown_session
from ray_tpu.util import placement_group, remove_placement_group


def _drain(q) -> list:
    """All currently queued items in one actor round-trip."""
    out: list = []
    while True:
        batch = q.get_batch(256)
        out.extend(batch)
        if len(batch) < 256:
            return out


class _TrainWorker:
    """One training worker (parity: the RayTrainWorker actor)."""

    def __init__(self, rank: int, world_size: int, local_rank: int,
                 local_world_size: int, node_rank: int,
                 rendezvous_env: Dict[str, str]):
        self.context = TrainContext(
            world_rank=rank, world_size=world_size, local_rank=local_rank,
            local_world_size=local_world_size, node_rank=node_rank,
        )
        self.rendezvous_env = dict(rendezvous_env)

    def get_env(self) -> Dict[str, str]:
        return self.rendezvous_env

    def configure_topology(self, local_rank: int, local_world_size: int,
                           node_rank: int) -> None:
        """Set node-local placement facts once actual placement is known
        (parity: BackendExecutor._create_rank_world_size_mappings)."""
        self.context.local_rank = local_rank
        self.context.local_world_size = local_world_size
        self.context.node_rank = node_rank

    def run(self, fn: Callable, report_queue,
            latest_checkpoint: Optional[Any] = None,
            config: Optional[Dict[str, Any]] = None) -> Any:
        rank = self.context.world_rank

        def report_fn(metrics, checkpoint):
            report_queue.put(
                {"rank": rank, "metrics": metrics, "checkpoint": checkpoint}
            )

        init_session(self.context, report_fn, latest_checkpoint)
        try:
            if config is not None:
                return fn(config)
            return fn()
        finally:
            shutdown_session()

    def execute(self, fn: Callable, *args, **kwargs) -> Any:
        """Arbitrary function on this worker (parity:
        WorkerGroup.execute's per-worker half)."""
        return fn(*args, **kwargs)


class WorkerGroup:
    """N worker actors gang-placed via a placement group (parity:
    WorkerGroup over the trial PG, air/execution placement)."""

    def __init__(self, num_workers: int, *,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_strategy: str = "PACK",
                 rendezvous_env: Optional[Dict[str, str]] = None):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = num_workers
        res = dict(resources_per_worker or {"CPU": 1})
        self._pg = placement_group([dict(res)] * num_workers,
                                   strategy=placement_strategy)
        ray_tpu.get(self._pg.ready())
        env = dict(rendezvous_env or {})
        env.setdefault("RAYTPU_COORDINATOR_ADDRESS", "127.0.0.1:0")
        cls = ray_tpu.remote(**_actor_opts(res))(_TrainWorker)
        self.workers = []
        for rank in range(num_workers):
            env_r = dict(env)
            env_r["RAYTPU_PROCESS_ID"] = str(rank)
            env_r["RAYTPU_NUM_PROCESSES"] = str(num_workers)
            self.workers.append(cls.options(
                placement_group=self._pg, placement_bundle_index=rank,
            ).remote(rank, num_workers, 0, 1, rank, env_r))
        self._configure_topology()

    def _configure_topology(self) -> None:
        """Group workers by the node they actually landed on and push
        local_rank / local_world_size / node_rank (parity:
        BackendExecutor's rank/world mappings — PACK co-locates workers,
        so node-local facts can't be assumed from the world rank)."""
        from ray_tpu.core import api

        rows = {row["actor_id"]: row.get("node_id")
                for row in api.runtime().actor_table()}
        node_of: List[Any] = [rows.get(w._actor_id.hex())
                              for w in self.workers]
        node_order: List[Any] = []
        members: Dict[Any, List[int]] = {}
        for rank, node in enumerate(node_of):
            if node not in members:
                members[node] = []
                node_order.append(node)
            members[node].append(rank)
        refs = []
        for node_rank, node in enumerate(node_order):
            ranks = members[node]
            for local_rank, rank in enumerate(ranks):
                refs.append(self.workers[rank].configure_topology.remote(
                    local_rank, len(ranks), node_rank
                ))
        ray_tpu.get(refs)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """fn on every worker; returns per-rank results (parity:
        WorkerGroup.execute)."""
        return ray_tpu.get([w.execute.remote(fn, *args, **kwargs)
                            for w in self.workers])

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            self.workers[rank].execute.remote(fn, *args, **kwargs)
        )

    def shutdown(self) -> None:
        for w in self.workers:
            ray_tpu.kill(w)
        remove_placement_group(self._pg)
        self.workers = []


def _actor_opts(res: Dict[str, float]) -> Dict[str, Any]:
    opts: Dict[str, Any] = {}
    if "CPU" in res:
        opts["num_cpus"] = res["CPU"]
    if "TPU" in res:
        opts["num_tpus"] = res["TPU"]
    extra = {k: v for k, v in res.items() if k not in ("CPU", "TPU")}
    if extra:
        opts["resources"] = extra
    return opts


@dataclasses.dataclass
class FailureConfig:
    """Whole-run retry budget (parity: air/config.py FailureConfig —
    max_failures retries of the trial from the latest checkpoint)."""

    max_failures: int = 0


class BackendExecutor:
    """Owns the worker group and the training launch (parity:
    _internal/backend_executor.py BackendExecutor — start:105,
    start_training:344)."""

    def __init__(self, num_workers: int, *,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_strategy: str = "PACK",
                 backend: Optional[Any] = None):
        self.num_workers = num_workers
        self.resources_per_worker = resources_per_worker
        self.placement_strategy = placement_strategy
        self.backend = backend
        self.worker_group: Optional[WorkerGroup] = None

    def start(self) -> None:
        self.worker_group = WorkerGroup(
            self.num_workers,
            resources_per_worker=self.resources_per_worker,
            placement_strategy=self.placement_strategy,
        )
        if self.backend is not None:
            # Form the jax.distributed world across the fresh worker
            # processes (parity: Backend.on_start building the NCCL
            # group, train/torch/config.py:63).
            self.backend.on_start(self.worker_group)

    def start_training(self, train_fn: Callable, report_queue,
                       latest_checkpoint: Optional[Any] = None,
                       config: Optional[Dict[str, Any]] = None):
        """Launch the user loop on every worker; returns the per-worker
        completion refs (results drained via report_queue meanwhile)."""
        assert self.worker_group is not None, "call start() first"
        return [
            w.run.remote(train_fn, report_queue, latest_checkpoint, config)
            for w in self.worker_group.workers
        ]

    def shutdown(self) -> None:
        if self.worker_group is not None:
            if self.backend is not None:
                self.backend.on_shutdown(self.worker_group)
            self.worker_group.shutdown()
            self.worker_group = None


class DataParallelTrainer:
    """train_loop_per_worker over a WorkerGroup (parity:
    train/data_parallel_trainer.py:59 — the reference's TorchTrainer
    base; the framework backend here is jax, so per-step gradient
    traffic is XLA collectives inside the loop, and this layer only
    orchestrates workers / reports / restarts)."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 num_workers: int = 1,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_strategy: str = "PACK",
                 failure_config: Optional[FailureConfig] = None,
                 backend: Optional[Any] = None):
        self._fn = train_loop_per_worker
        self._config = train_loop_config
        self._num_workers = num_workers
        self._resources = resources_per_worker
        self._strategy = placement_strategy
        self._failure_config = failure_config or FailureConfig()
        self._backend = backend

    def fit(self) -> "TrainOutput":
        from ray_tpu.util.queue import Queue

        attempts = self._failure_config.max_failures + 1
        last_error: Optional[BaseException] = None
        latest_checkpoint: Optional[Any] = None
        # Reports accumulate across restart attempts (the failed
        # attempt's progress is part of the run's history).
        history: List[Dict[str, Any]] = []
        for _attempt in range(attempts):
            executor = BackendExecutor(
                self._num_workers,
                resources_per_worker=self._resources,
                placement_strategy=self._strategy,
                backend=self._backend,
            )
            executor.start()
            report_queue = Queue()
            refs = executor.start_training(
                self._fn, report_queue, latest_checkpoint, self._config
            )
            def absorb_reports():
                # Resume keys off rank 0's checkpoints only (parity:
                # the reference persists the rank-0 report; a slow rank
                # must not roll back a newer rank-0 checkpoint).
                nonlocal latest_checkpoint
                for item in _drain(report_queue):
                    history.append(item)
                    if item.get("checkpoint") is not None \
                            and item["rank"] == 0:
                        latest_checkpoint = item["checkpoint"]

            try:
                pending = list(refs)
                while pending:
                    absorb_reports()
                    done, pending = ray_tpu.wait(
                        pending, num_returns=len(pending), timeout=0.05
                    )
                    if done:
                        ray_tpu.get(done)  # surface worker errors
                # Drain any reports that landed after the last wait.
                absorb_reports()
                returns = ray_tpu.get(refs)
                report_queue.shutdown()
                executor.shutdown()
                return TrainOutput(
                    metrics=(history[-1]["metrics"] if history else {}),
                    metrics_history=history,
                    checkpoint=latest_checkpoint,
                    worker_returns=returns,
                    error=None,
                )
            except BaseException as e:
                # Stop the workers first (their report() must not race a
                # dying queue), then capture reports — including the
                # newest rank-0 checkpoint — then drop the queue actor.
                executor.shutdown()
                absorb_reports()
                report_queue.shutdown()
                if not isinstance(e, Exception):
                    raise  # KeyboardInterrupt etc: cleaned up, propagate
                last_error = e
                # retry from latest checkpoint (parity: FailureConfig
                # whole-run restart)
                continue
        return TrainOutput(metrics=(history[-1]["metrics"] if history
                                    else {}),
                           metrics_history=history,
                           checkpoint=latest_checkpoint,
                           worker_returns=None, error=last_error)


@dataclasses.dataclass
class TrainOutput:
    """fit() result (parity: air Result for the worker-group path)."""

    metrics: Dict[str, Any]
    metrics_history: List[Dict[str, Any]]
    checkpoint: Optional[Any]
    worker_returns: Optional[List[Any]]
    error: Optional[BaseException]
