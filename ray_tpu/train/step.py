"""The sharded training step.

One jitted SPMD program spans the whole mesh: forward, backward,
optimizer update.  Gradient reduction over dp/fsdp, parameter
all-gathers under fsdp, and tp collectives are all inserted by the GSPMD
partitioner from the sharding annotations — the step function contains
no explicit communication (contrast the reference, where NCCL allreduce
hides inside torch DDP; ray: python/ray/train/torch/config.py:63).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.sharding import Rules, tree_shardings
from ray_tpu.train.state import TrainState, state_shardings
from ray_tpu.util import tracing

_TELEMETRY = None


def _telemetry():
    """Step-compilation metric singleton (re-registered on refetch —
    see serve/llm_engine._telemetry for the registry-clear rationale)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "compile": metrics.Counter(
                "raytpu_train_compile_seconds_total",
                "Seconds spent in first-call XLA compilation of train "
                "steps.",
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


def _instrument_first_call(jitted):
    """The first invocation of a jitted step traces + compiles the XLA
    program; time it so compile cost shows up next to step time in the
    registry and the timeline.  Subsequent calls pass straight through."""
    compiled = []

    def wrapped(state, batch):
        if compiled:
            return jitted(state, batch)
        # Lower BEFORE executing: the step donates ``state``, so after
        # the call those buffers are gone and cost analysis would have
        # nothing to trace against.
        lowered = None
        try:
            lowered = jitted.lower(state, batch)
        except Exception:
            pass
        t0 = time.time()
        out = jitted(state, batch)
        compiled.append(True)
        elapsed = time.time() - t0
        _telemetry()["compile"].inc(elapsed)
        tracing.record_span("train.compile", t0, t0 + elapsed)
        if lowered is not None:
            try:
                from ray_tpu.util import xprof

                xprof.record_compiled(
                    "train.step", lowered, compile_time_s=elapsed,
                    span_name="train.compute")
            except Exception:
                pass  # device-plane attribution is best-effort
        return out

    wrapped.__wrapped__ = jitted
    return wrapped

LossFn = Callable[[Any, Dict[str, jax.Array]], Tuple[jax.Array, Dict[str, jax.Array]]]


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    *,
    grad_accum: int = 1,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Returns step(state, batch) -> (state, metrics). Pure; jit outside.

    ``grad_accum > 1`` scans the batch as that many microbatches along
    the leading dim, accumulating grads before the single optimizer
    update — same math (mean-of-means for equal microbatches), 1/k the
    activation memory, which is what lets a full-8B step fit.
    """

    def _grads(state, batch):
        if grad_accum <= 1:
            return jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, batch)

        def split(x):
            if x.shape[0] % grad_accum:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by "
                    f"grad_accum={grad_accum}")
            return x.reshape(grad_accum, x.shape[0] // grad_accum,
                             *x.shape[1:])

        micro = jax.tree.map(split, batch)

        def body(carry, mb):
            loss_sum, gsum = carry
            (l, a), g = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, mb)
            return (loss_sum + l.astype(jnp.float32),
                    jax.tree.map(jnp.add, gsum, g)), a

        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype),
                             state.params)
        (loss_sum, gsum), auxs = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros), micro)
        loss = loss_sum / grad_accum
        grads = jax.tree.map(lambda g: g / grad_accum, gsum)
        aux = jax.tree.map(lambda x: x[-1], auxs)
        return (loss, aux), grads

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        (loss, aux), grads = _grads(state, batch)
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        # Canonical keys win over aux duplicates: under grad_accum the
        # aux rides from the last microbatch only, while ``loss`` is
        # the mean over all of them.
        metrics = {**aux, "loss": loss, "grad_norm": gnorm,
                   "step": state.step}
        return (
            TrainState(state.step + 1, new_params, new_opt_state),
            metrics,
        )

    return step


def compile_train_step(
    mesh,
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    state: TrainState,
    params_axes: Any,
    batch_axes: Dict[str, Tuple[Optional[str], ...]],
    rules: Optional[Rules] = None,
    *,
    zero_sharding: bool = False,
    grad_accum: int = 1,
):
    """Jit the step with explicit in/out shardings over ``mesh``.

    ``zero_sharding=True`` pins the optimizer state to the ZeRO layout
    (train/zero.py) in BOTH in_ and out_shardings — the state stays
    donation-safe (matched layouts), and forcing the update's outputs
    sharded is what makes GSPMD reduce-scatter the grads instead of
    all-reducing them.

    Returns (jitted_step, state_shardings_tree, batch_shardings_tree).
    """
    step = make_train_step(loss_fn, tx, grad_accum=grad_accum)
    st_sh = state_shardings(mesh, state, params_axes, rules,
                            zero=zero_sharding)
    batch_sh = {k: tree_shardings(mesh, v, rules) for k, v in batch_axes.items()}
    jitted = jax.jit(
        step,
        in_shardings=(st_sh, batch_sh),
        out_shardings=(st_sh, None),
        donate_argnums=(0,),
    )
    return _instrument_first_call(jitted), st_sh, batch_sh
