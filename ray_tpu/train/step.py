"""The sharded training step.

One jitted SPMD program spans the whole mesh: forward, backward,
optimizer update.  Gradient reduction over dp/fsdp, parameter
all-gathers under fsdp, and tp collectives are all inserted by the GSPMD
partitioner from the sharding annotations — the step function contains
no explicit communication (contrast the reference, where NCCL allreduce
hides inside torch DDP; ray: python/ray/train/torch/config.py:63).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.sharding import Rules, tree_shardings
from ray_tpu.train.state import TrainState, state_shardings
from ray_tpu.util import tracing

_TELEMETRY = None


def _telemetry():
    """Step-compilation metric singleton (re-registered on refetch —
    see serve/llm_engine._telemetry for the registry-clear rationale)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "compile": metrics.Counter(
                "raytpu_train_compile_seconds_total",
                "Seconds spent in first-call XLA compilation of train "
                "steps.",
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


def _instrument_first_call(jitted):
    """The first invocation of a jitted step traces + compiles the XLA
    program; time it so compile cost shows up next to step time in the
    registry and the timeline.  Subsequent calls pass straight through."""
    compiled = []

    def wrapped(state, batch):
        if compiled:
            return jitted(state, batch)
        # Lower BEFORE executing: the step donates ``state``, so after
        # the call those buffers are gone and cost analysis would have
        # nothing to trace against.
        lowered = None
        try:
            lowered = jitted.lower(state, batch)
        except Exception:
            pass
        t0 = time.time()
        out = jitted(state, batch)
        compiled.append(True)
        elapsed = time.time() - t0
        _telemetry()["compile"].inc(elapsed)
        tracing.record_span("train.compile", t0, t0 + elapsed)
        if lowered is not None:
            try:
                from ray_tpu.util import xprof

                xprof.record_compiled(
                    "train.step", lowered, compile_time_s=elapsed,
                    span_name="train.compute")
            except Exception:
                pass  # device-plane attribution is best-effort
        return out

    wrapped.__wrapped__ = jitted
    return wrapped

LossFn = Callable[[Any, Dict[str, jax.Array]], Tuple[jax.Array, Dict[str, jax.Array]]]


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Returns step(state, batch) -> (state, metrics). Pure; jit outside."""

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch
        )
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        metrics = {"loss": loss, "grad_norm": gnorm, "step": state.step, **aux}
        return (
            TrainState(state.step + 1, new_params, new_opt_state),
            metrics,
        )

    return step


def compile_train_step(
    mesh,
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    state: TrainState,
    params_axes: Any,
    batch_axes: Dict[str, Tuple[Optional[str], ...]],
    rules: Optional[Rules] = None,
):
    """Jit the step with explicit in/out shardings over ``mesh``.

    Returns (jitted_step, state_shardings_tree, batch_shardings_tree).
    """
    step = make_train_step(loss_fn, tx)
    st_sh = state_shardings(mesh, state, params_axes, rules)
    batch_sh = {k: tree_shardings(mesh, v, rules) for k, v in batch_axes.items()}
    jitted = jax.jit(
        step,
        in_shardings=(st_sh, batch_sh),
        out_shardings=(st_sh, None),
        donate_argnums=(0,),
    )
    return _instrument_first_call(jitted), st_sh, batch_sh
