"""Train state: params + optimizer state + step, sharding-aware.

Replaces the reference's framework-wrapper approach (ray:
python/ray/train/torch/train_loop_utils.py prepare_model/DDP/FSDP) with
a GSPMD-native one: optimizer state inherits the params' logical axes,
so FSDP-style (ZeRO) sharding of Adam moments falls out of the same rule
table that shards the params (cf. PAPERS.md "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training").
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.sharding import Rules, tree_shardings


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def create_train_state(params: Any, tx: optax.GradientTransformation) -> TrainState:
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
    )


def _is_axes_leaf(x) -> bool:
    return isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x)


def state_logical_axes(state: TrainState, params_axes: Any) -> TrainState:
    """Logical axes for a whole TrainState, derived from the params' axes.

    Optimizer-state leaves that mirror a param (same shape) inherit its
    axes; scalars/others replicate.
    """
    flat_axes = jax.tree.leaves(params_axes, is_leaf=_is_axes_leaf)
    params_struct = jax.tree.structure(state.params)

    def annotate_like(opt_tree):
        """Map each optimizer-state subtree: if it has the same structure
        as params, zip with params_axes; else replicate leaves."""

        def rec(node):
            if jax.tree.structure(node) == params_struct:
                return jax.tree.unflatten(params_struct, flat_axes)
            if isinstance(node, (dict,)):
                return {k: rec(v) for k, v in node.items()}
            if isinstance(node, tuple) and hasattr(node, "_fields"):
                return type(node)(*[rec(v) for v in node])
            if isinstance(node, (list, tuple)):
                return type(node)(rec(v) for v in node)
            # leaf: replicate (scalars like counts, schedules)
            ndim = getattr(node, "ndim", 0)
            return tuple([None] * ndim)

        return rec(opt_tree)

    return TrainState(
        step=(),
        params=jax.tree.unflatten(params_struct, flat_axes),
        opt_state=annotate_like(state.opt_state),
    )


def state_shardings(
    mesh,
    state: TrainState,
    params_axes: Any,
    rules: Optional[Rules] = None,
    *,
    zero: bool = False,
) -> TrainState:
    """Shardings for a whole TrainState.  ``zero=True`` switches to the
    ZeRO layout (train/zero.py): optimizer state — including optim8's
    int8 (q, scale) blockwise leaves, which the mirror-structure check
    below can only replicate — shards over the data axes."""
    if zero:
        from ray_tpu.train.zero import zero_state_shardings

        return zero_state_shardings(mesh, state, params_axes, rules)
    axes = state_logical_axes(state, params_axes)
    return jax.tree.map(
        lambda a: tree_shardings(mesh, a, rules),
        axes,
        is_leaf=_is_axes_leaf,
    )


def default_optimizer(
    learning_rate: float | Callable = 3e-4,
    *,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    total_steps: Optional[int] = None,
    mu_dtype: Any = None,
) -> optax.GradientTransformation:
    """AdamW with cosine schedule + global-norm clipping (LLM defaults).
    ``mu_dtype=jnp.bfloat16`` halves the first-moment buffer (HBM
    headroom for bigger batches; the variance stays float32)."""
    if callable(learning_rate):
        schedule = learning_rate
    elif total_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
        )
    else:
        schedule = optax.linear_schedule(0.0, learning_rate, max(warmup_steps, 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )
