"""Checkpoint save/restore via orbax.

Parity with the reference checkpoint flow (ray: train/_internal/storage.py
StorageContext + checkpoint_manager.py keep-top-K): orbax writes sharded
arrays directly from device memory (each host writes its shards — no
gather), with a step-numbered directory layout and retention.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, *, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Any, *, metrics: Optional[dict] = None,
             wait: bool = False) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state), metrics=metrics)
        if wait:
            self._mngr.wait_until_finished()

    def restore(self, state_like: Any, *, step: Optional[int] = None) -> Any:
        step = step if step is not None else self._mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        return self._mngr.restore(step, args=ocp.args.StandardRestore(state_like))

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def close(self):
        self._mngr.wait_until_finished()
        self._mngr.close()
