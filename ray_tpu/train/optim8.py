"""Block-wise 8-bit Adam optimizer states (TPU-native bitsandbytes
analogue).

The reference ecosystem fits big models with 8-bit optimizers
(bitsandbytes' CUDA kernels); on TPU the same memory play is plain XLA:
Adam's m/v tensors live as int8 with one float32 absmax scale per
256-element block, dequantized/requantized inside the fused update —
2 bytes/param of optimizer state instead of 8, which is what lets a
~2.4B-param AdamW config train on one 16 GB chip (bench.py's measured
multi-billion point).  Quantization error behaves like rounding noise
on m/v; each block keeps full dynamic range via its own scale.
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

BLOCK = 256


def _quantize(x: jax.Array):
    """flat float32 → (int8 [nb, BLOCK], f32 scale [nb, 1])."""
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.shape[0]) % BLOCK
    blocks = jnp.pad(flat, (0, pad)).reshape(-1, BLOCK)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    return (q, scale)

def _dequantize(s, shape) -> jax.Array:
    q, scale = s
    n = math.prod(shape)
    flat = (q.astype(jnp.float32) * scale).reshape(-1)
    return flat[:n].reshape(shape)


class ScaleByAdam8State(NamedTuple):
    count: Any
    mu: Any   # pytree with (q, scale) tuples at param leaf positions
    nu: Any


def _constrain_blocks(x: jax.Array, dim: int = 0) -> jax.Array:
    """Pin the block dim of an int8-Adam buffer to the ZeRO shard axes
    of whatever mesh encloses the trace (train/zero.py's layout), so
    the partitioner keeps the blockwise update local to each shard
    instead of gathering state — the reduce-scatter → local-update →
    all-gather pattern of arXiv 2004.13336.  No-op outside a mesh or
    when the block count doesn't divide the shard axes."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.sharding import constrain_to_spec, current_mesh
    from ray_tpu.train import zero as zero_mod

    mesh = current_mesh()
    if mesh is None:
        return x
    ax = zero_mod.shardable_prefix(
        x.shape[dim], zero_mod.zero_axes(mesh), mesh)
    if not ax:
        return x
    entries = [None] * x.ndim
    entries[dim] = ax[0] if len(ax) == 1 else ax
    return constrain_to_spec(x, P(*entries))


def scale_by_adam8bit(b1: float = 0.9, b2: float = 0.95,
                      eps: float = 1e-8, *, shard_update: bool = False
                      ) -> optax.GradientTransformation:
    """Adam moment tracking with int8 block-quantized mu/nu.

    ``shard_update=True`` adds ZeRO sharding constraints on the block
    dim of every buffer entering/leaving the fused update (grads in
    block space, the segment-stacked m/v, and their replacements), for
    use with ``TrainerConfig(zero_sharding=True)``."""

    def init(params):
        q0 = lambda p: _quantize(jnp.zeros(p.shape, jnp.float32))
        return ScaleByAdam8State(
            jnp.zeros([], jnp.int32),
            jax.tree.map(q0, params),
            jax.tree.map(q0, params),
        )

    def update(grads, state, params=None):
        count = state.count + 1
        cf = count.astype(jnp.float32)

        def upd(g, mq, nq):
            # The whole update runs in BLOCK space, streamed over
            # segments with lax.map: dequantizing a multi-hundred-M
            # stacked leaf's m, v, and grads to f32 at once is
            # ~5 x leaf f32 bytes of transient HBM — the difference
            # between a 2.2B model fitting a 16 GB chip or not.
            shape, dt = g.shape, g.dtype
            nb = mq[0].shape[0]
            pad = nb * BLOCK - math.prod(shape)
            gb = jnp.pad(g.reshape(-1), (0, pad)).reshape(nb, BLOCK)
            if shard_update:
                gb = _constrain_blocks(gb)
            nseg = min(16, nb)
            segp = (-nb) % nseg
            def seg(args):
                gs, mqs, mss, nqs, nss = args
                g32 = gs.astype(jnp.float32)
                m = mqs.astype(jnp.float32) * mss
                # nu stored as sqrt(v): linear int8 only spans a 127:1
                # ratio per block — storing the root doubles the
                # covered dynamic range, which is the difference
                # between converging and small-v blocks rounding to 0
                # (update explosion).  (bitsandbytes uses a nonlinear
                # dynamic code for the same reason.)
                u = nqs.astype(jnp.float32) * nss
                n = b2 * (u * u) + (1 - b2) * (g32 * g32)
                m = b1 * m + (1 - b1) * g32
                mhat = m / (1 - b1 ** cf)
                nhat = n / (1 - b2 ** cf)
                out = mhat / (jnp.sqrt(nhat) + eps)
                out = jnp.clip(out, -10.0, 10.0).astype(dt)
                ms2 = jnp.maximum(
                    jnp.max(jnp.abs(m), axis=1, keepdims=True) / 127.0,
                    1e-12)
                mq2 = jnp.clip(jnp.round(m / ms2), -127, 127
                               ).astype(jnp.int8)
                un = jnp.sqrt(n)
                ns2 = jnp.maximum(
                    jnp.max(un, axis=1, keepdims=True) / 127.0, 1e-12)
                nq2 = jnp.clip(jnp.round(un / ns2), -127, 127
                               ).astype(jnp.int8)
                return out, mq2, ms2, nq2, ns2

            def segify(x):
                if segp:
                    x = jnp.concatenate(
                        [x, jnp.zeros((segp,) + x.shape[1:], x.dtype)])
                return x.reshape(nseg, -1, *x.shape[1:])

            args = tuple(segify(a) for a in
                         (gb, mq[0], mq[1], nq[0], nq[1]))
            if shard_update:
                args = tuple(_constrain_blocks(a, dim=1) for a in args)
            out, mq2, ms2, nq2, ns2 = jax.lax.map(seg, args)
            if shard_update:
                mq2, ms2, nq2, ns2 = (
                    _constrain_blocks(a, dim=1)
                    for a in (mq2, ms2, nq2, ns2))
            out = out.reshape(-1)[: math.prod(shape)].reshape(shape)

            def unseg(x):
                x = x.reshape(-1, *x.shape[2:])
                return x[:nb] if segp else x

            return (out, (unseg(mq2), unseg(ms2)),
                    (unseg(nq2), unseg(ns2)))

        flat_g, treedef = jax.tree.flatten(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_n = treedef.flatten_up_to(state.nu)
        outs = [upd(g, m, n) for g, m, n in zip(flat_g, flat_m, flat_n)]
        return (treedef.unflatten([o[0] for o in outs]),
                ScaleByAdam8State(count,
                                  treedef.unflatten([o[1] for o in outs]),
                                  treedef.unflatten([o[2] for o in outs])))

    return optax.GradientTransformation(init, update)


def adamw8bit(
    learning_rate: float = 3e-4,
    *,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    total_steps: Optional[int] = None,
    shard_update: bool = False,
) -> optax.GradientTransformation:
    """AdamW with 8-bit states + the same schedule/clipping wrapping as
    train.default_optimizer.  ``shard_update=True`` enables the ZeRO
    block-dim sharding constraints (see scale_by_adam8bit)."""
    if total_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, warmup_steps,
            max(total_steps, warmup_steps + 1))
    else:
        schedule = optax.linear_schedule(
            0.0, learning_rate, max(1, warmup_steps))
    parts = []
    if grad_clip:
        parts.append(optax.clip_by_global_norm(grad_clip))
    parts.append(scale_by_adam8bit(b1=b1, b2=b2, eps=eps,
                                   shard_update=shard_update))
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.scale_by_learning_rate(schedule))
    return optax.chain(*parts)
