"""JaxTrainer — the Train-equivalent entry point.

API parity with the reference's DataParallelTrainer/TorchTrainer
(ray: python/ray/train/data_parallel_trainer.py:59,
train/torch/torch_trainer.py:14, base_trainer.py:608 fit()), redesigned
for SPMD: instead of N worker processes each running a copy of a
training loop synchronized by NCCL, one logical program is jitted over a
device mesh; scaling config is a ``MeshSpec`` rather than
``num_workers``.  Multi-host operation reuses the same code — the actor
layer (ray_tpu.core) pins one controller process per host and jax's
distributed runtime makes ``jax.devices()`` span hosts.

``fit()`` is usable standalone (the reference inverts this by routing
fit() through Tune; see SURVEY.md §7 phase 6 note).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax
import numpy as np

from ray_tpu.parallel.mesh import MeshSpec, create_mesh
from ray_tpu.parallel.sharding import Rules
from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.state import TrainState, create_train_state, default_optimizer
from ray_tpu.train.step import compile_train_step
from ray_tpu.util import tracing, xprof

_TELEMETRY = None


def _telemetry():
    """Trainer metric singletons (re-registered on refetch — see
    serve/llm_engine._telemetry for the registry-clear rationale)."""
    global _TELEMETRY
    from ray_tpu.util import metrics

    if _TELEMETRY is None:
        _TELEMETRY = {
            "step_s": metrics.Histogram(
                "raytpu_train_step_seconds",
                "Host-side duration of one training step (dispatch, plus "
                "device sync on report steps).",
                boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                            5.0, 30.0, 120.0],
            ),
            "data_wait_s": metrics.Histogram(
                "raytpu_train_data_wait_seconds",
                "Seconds each step waited on the input iterator + batch "
                "sharding.",
                boundaries=[0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                            1.0, 5.0],
            ),
            "steps": metrics.Counter(
                "raytpu_train_steps_total",
                "Training steps completed.",
            ),
            "checkpoints": metrics.Counter(
                "raytpu_train_checkpoints_total",
                "Checkpoints written by the trainer.",
            ),
            "opt_bytes": metrics.Gauge(
                "raytpu_train_opt_state_bytes",
                "Optimizer-state footprint from the arrays' shardings: "
                "scope=global across the mesh, scope=per_device resident "
                "on one device (~global/dp under ZeRO sharding).",
                tag_keys=("scope",),
            ),
            "hbm_headroom": metrics.Gauge(
                "raytpu_train_hbm_headroom_bytes",
                "Per-device HBM left above the peak watermark "
                "(bytes_limit - peak_bytes_in_use), sampled on report "
                "steps; absent on backends without memory_stats (CPU).",
                tag_keys=("device",),
            ),
        }
    else:
        reg = metrics.registry()
        for m in _TELEMETRY.values():
            reg.register(m)
    return _TELEMETRY


@dataclasses.dataclass
class ScalingConfig:
    """Parity: air.ScalingConfig(num_workers, use_gpu) → mesh layout."""

    mesh_spec: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    devices: Optional[list] = None  # default: all


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    """Step-program options.

    ``zero_sharding`` shards the optimizer state (and the weight
    update) across the data axes, ZeRO-style — grads reduce-scatter,
    each replica updates 1/dp of the blocks, params all-gather back
    (train/zero.py).  ``grad_accum`` scans each batch as that many
    microbatches before the single update (train/step.py)."""

    zero_sharding: bool = False
    grad_accum: int = 1


@dataclasses.dataclass
class RunConfig:
    """Parity: air.RunConfig (name, storage_path, checkpoint/failure cfg)."""

    name: str = "run"
    storage_path: Optional[str] = None
    checkpoint_every: int = 0  # steps; 0 = only final
    checkpoints_to_keep: int = 3
    report_every: int = 10


@dataclasses.dataclass
class Result:
    """Parity: air.Result (metrics, checkpoint path, error)."""

    metrics: Dict[str, float]
    metrics_history: List[Dict[str, float]]
    checkpoint_path: Optional[str]
    error: Optional[BaseException] = None


class JaxTrainer:
    def __init__(
        self,
        *,
        init_params: Callable[[jax.Array], Any],
        loss_fn: Callable[[Any, Dict[str, jax.Array]], Tuple[jax.Array, Dict]],
        params_axes: Any,
        batch_axes: Dict[str, Tuple[Optional[str], ...]],
        optimizer=None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        trainer_config: Optional[TrainerConfig] = None,
        rules: Optional[Rules] = None,
        seed: int = 0,
    ):
        self.init_params_fn = init_params
        self.loss_fn = loss_fn
        self.params_axes = params_axes
        self.batch_axes = batch_axes
        self.tx = optimizer or default_optimizer()
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.trainer_config = trainer_config or TrainerConfig()
        self.rules = rules
        self.seed = seed

        self.mesh = create_mesh(self.scaling.mesh_spec,
                                devices=self.scaling.devices)
        self._state: Optional[TrainState] = None
        self._step_fn = None
        self._state_sh = None
        self._batch_sh = None

    # -- setup -------------------------------------------------------------

    def _build(self):
        rng = jax.random.key(self.seed)
        with self.mesh:
            abstract = jax.eval_shape(
                lambda r: create_train_state(self.init_params_fn(r), self.tx), rng
            )
            # Compile the step against abstract state to get shardings first.
            self._step_fn, self._state_sh, self._batch_sh = compile_train_step(
                self.mesh, self.loss_fn, self.tx, abstract, self.params_axes,
                self.batch_axes, self.rules,
                zero_sharding=self.trainer_config.zero_sharding,
                grad_accum=self.trainer_config.grad_accum,
            )
            # Init params *directly sharded* — no host-memory full copy, so
            # 70B-scale states can initialize on the mesh.
            init = jax.jit(
                lambda r: create_train_state(self.init_params_fn(r), self.tx),
                out_shardings=self._state_sh,
            )
            self._state = init(rng)
        self._emit_memory_gauges()

    def _emit_memory_gauges(self):
        """Opt-state footprint from the live arrays' shardings, plus
        per-device HBM headroom (absent-not-zero on CPU backends)."""
        from ray_tpu.train import zero as zero_mod

        tm = _telemetry()
        b = zero_mod.opt_state_bytes(self._state.opt_state)
        tm["opt_bytes"].set(b["global"], tags={"scope": "global"})
        tm["opt_bytes"].set(b["per_device"], tags={"scope": "per_device"})
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                return
            if not stats or "bytes_limit" not in stats:
                continue
            peak = stats.get("peak_bytes_in_use",
                             stats.get("bytes_in_use", 0))
            tm["hbm_headroom"].set(
                stats["bytes_limit"] - peak,
                tags={"device": f"{d.platform}:{d.id}"})

    @property
    def state(self) -> TrainState:
        if self._state is None:
            self._build()
        return self._state

    def restore(self, path: str) -> int:
        """Resume from latest checkpoint under ``path``; returns step."""
        if self._state is None:
            self._build()
        mngr = CheckpointManager(path)
        self._state = mngr.restore(self._state)
        mngr.close()
        return int(jax.device_get(self._state.step))

    # -- training ----------------------------------------------------------

    def shard_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        return jax.device_put(batch, self._batch_sh)

    def fit(
        self,
        data: Iterable[Dict[str, np.ndarray]],
        *,
        num_steps: int,
        report: Optional[Callable[[Dict[str, float]], None]] = None,
    ) -> Result:
        if self._state is None:
            self._build()
        rc = self.run_config
        ckpt = None
        if rc.storage_path:
            ckpt = CheckpointManager(
                f"{rc.storage_path}/{rc.name}", max_to_keep=rc.checkpoints_to_keep
            )

        history: List[Dict[str, float]] = []
        last_metrics: Dict[str, float] = {}
        tm = _telemetry()
        it = iter(data)
        t0 = time.perf_counter()
        error: Optional[BaseException] = None
        try:
            with self.mesh:
                for i in range(num_steps):
                    step = i + 1
                    with tracing.span("train.step",
                                      attributes={"step": step}):
                        w0 = time.perf_counter()
                        with tracing.span("train.data_wait"):
                            batch = self.shard_batch(next(it))
                        c0 = time.perf_counter()
                        tm["data_wait_s"].observe(c0 - w0)
                        # Host-side timing: jax dispatch is async, so
                        # off-report steps measure dispatch cost; report
                        # steps sync below via device_get.
                        with tracing.span("train.compute"):
                            self._state, metrics = self._step_fn(
                                self._state, batch)
                        tm["step_s"].observe(time.perf_counter() - c0)
                        tm["steps"].inc()
                        if step % rc.report_every == 0 or step == num_steps:
                            m = {k: float(jax.device_get(v))
                                 for k, v in metrics.items()}
                            m["steps_per_sec"] = step / (
                                time.perf_counter() - t0)
                            history.append(m)
                            last_metrics = m
                            # Shared device-plane sampler (TPU/GPU HBM
                            # watermarks; absent on CPU backends).
                            xprof.sample_device_memory()
                            self._emit_memory_gauges()
                            if report:
                                report(m)
                        if ckpt and rc.checkpoint_every \
                                and step % rc.checkpoint_every == 0:
                            # sharded arrays go straight to orbax — each
                            # host writes its own shards, no host gather
                            with tracing.span("train.checkpoint",
                                              attributes={"step": step}):
                                ckpt.save(step, self._state)
                            tm["checkpoints"].inc()
        except BaseException as e:  # report partial progress + the failure
            error = e
            if not isinstance(e, Exception):
                raise
        finally:
            path = None
            if ckpt:
                final_step = int(jax.device_get(self._state.step))
                if error is None and ckpt.latest_step() != final_step:
                    ckpt.save(final_step, self._state, wait=True)
                else:
                    ckpt._mngr.wait_until_finished()
                path = f"{rc.storage_path}/{rc.name}"
                ckpt.close()
        return Result(
            metrics=last_metrics,
            metrics_history=history,
            checkpoint_path=path,
            error=error,
        )
