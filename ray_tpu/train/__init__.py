from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.state import (
    TrainState,
    create_train_state,
    default_optimizer,
    state_shardings,
)
from ray_tpu.train.step import compile_train_step, make_train_step
from ray_tpu.train.trainer import JaxTrainer, Result, RunConfig, ScalingConfig

__all__ = [
    "CheckpointManager",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainState",
    "compile_train_step",
    "create_train_state",
    "default_optimizer",
    "make_train_step",
    "state_shardings",
]
