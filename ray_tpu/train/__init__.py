from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.optim8 import adamw8bit, scale_by_adam8bit
from ray_tpu.train.state import (
    TrainState,
    create_train_state,
    default_optimizer,
    state_shardings,
)
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    report,
)
from ray_tpu.train.step import compile_train_step, make_train_step
from ray_tpu.train.trainer import (
    JaxTrainer,
    Result,
    RunConfig,
    ScalingConfig,
    TrainerConfig,
)
from ray_tpu.train import zero
from ray_tpu.train.backend import JaxBackendConfig, JaxDistributedBackend
from ray_tpu.train.worker_group import (
    BackendExecutor,
    DataParallelTrainer,
    FailureConfig,
    TrainOutput,
    WorkerGroup,
)

__all__ = [
    "BackendExecutor",
    "CheckpointManager",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxBackendConfig",
    "JaxDistributedBackend",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainContext",
    "TrainOutput",
    "TrainState",
    "TrainerConfig",
    "WorkerGroup",
    "zero",
    "compile_train_step",
    "create_train_state",
    "adamw8bit",
    "default_optimizer",
    "scale_by_adam8bit",
    "get_checkpoint",
    "get_context",
    "make_train_step",
    "report",
    "state_shardings",
]
