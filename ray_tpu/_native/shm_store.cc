// Shared-memory object store — the plasma equivalent, C++.
//
// Parity with the reference's plasma store (ray:
// src/ray/object_manager/plasma/store.h:55, object_lifecycle_manager.h,
// eviction_policy.h, plasma_allocator.h): immutable objects in a
// shared-memory arena, create→seal lifecycle, refcounted gets, LRU
// eviction of sealed unreferenced objects under pressure.  Differences,
// deliberate: the arena is one POSIX shm segment mapped by every process
// (the reference passes fds over a unix socket — fling.cc); the object
// index lives *inside* the segment guarded by a robust process-shared
// mutex, so there is no store server process to round-trip to for
// create/get — TPU-host data loading wants the lowest possible
// per-object overhead, not a socket protocol.
//
// Build: g++ -O2 -shared -fPIC -o libshm_store.so shm_store.cc -lpthread -lrt
// C ABI for ctypes.  All functions return 0 on success, negative errno-style
// codes on failure.

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Magic doubles as the layout version: any change to Header/Slot/
// FreeBlock layout MUST bump it so a mixed-build process gets a clean
// -EINVAL on attach instead of silently mis-striding the slot table.
constexpr uint64_t kMagic = 0x7470755f73743032ULL;  // "tpu_st02"
constexpr int kIdSize = 32;
constexpr uint32_t kFreeListCap = 4096;

enum SlotState : uint32_t {
  SLOT_EMPTY = 0,
  SLOT_CREATED = 1,    // allocated, producer writing
  SLOT_SEALED = 2,     // immutable, readable
  SLOT_TOMBSTONE = 3,  // deleted/evicted; keeps hash probe chains intact
};

struct Slot {
  uint8_t id[kIdSize];
  uint32_t state;
  uint32_t refcount;    // outstanding gets
  uint64_t offset;      // into data arena
  uint64_t size;
  uint64_t lru_tick;    // last touch
  uint64_t creator_pid; // producer of a CREATED slot; abort is creator-only
};

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;       // data arena bytes
  uint64_t data_start;     // offset of arena from segment base
  uint32_t num_slots;
  uint32_t free_count;
  uint64_t bump;           // high-water mark in arena
  uint64_t lru_clock;
  uint64_t bytes_used;
  uint64_t num_objects;
  uint64_t evictions;
  uint32_t tombstones;
  uint32_t pad_;
  pthread_mutex_t mutex;
  // followed by: Slot[num_slots], FreeBlock[kFreeListCap], arena
};

struct Store {
  Header* hdr;
  uint8_t* base;
  uint64_t map_size;
  int fd;
  bool owner;
  char name[256];
};

Slot* slots(Header* h) {
  return reinterpret_cast<Slot*>(reinterpret_cast<uint8_t*>(h) + sizeof(Header));
}

FreeBlock* free_list(Header* h) {
  return reinterpret_cast<FreeBlock*>(
      reinterpret_cast<uint8_t*>(slots(h)) + sizeof(Slot) * h->num_slots);
}

uint8_t* arena(Store* s) { return s->base + s->hdr->data_start; }

void rebuild_allocator(Header* h);

class Guard {
 public:
  explicit Guard(Header* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->mutex);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock.  Allocator mutations are
      // multi-word, so assume the free list / counters are torn and
      // rebuild them from the slot table (the authoritative record:
      // every slot mutation is a single state-word transition last).
      pthread_mutex_consistent(&h_->mutex);
      rebuild_allocator(h_);
    }
  }
  ~Guard() { pthread_mutex_unlock(&h_->mutex); }

 private:
  Header* h_;
};

// True iff the process that created an unsealed slot no longer exists
// (kill(pid, 0) probe).  Lets orphaned CREATED slots — producer died
// mid-write — be reclaimed by eviction, delete, or a peer's abort.
// pid reuse can delay reclamation until the imposter exits; never
// causes premature frees because live producers always match getpid().
bool producer_dead(const Slot* s) {
  if (s->creator_pid == 0) return true;
  return kill((pid_t)s->creator_pid, 0) != 0 && errno == ESRCH;
}

// FNV-1a over the 32-byte id.
uint64_t hash_id(const uint8_t* id) {
  uint64_t x = 1469598103934665603ULL;
  for (int i = 0; i < kIdSize; i++) {
    x = (x ^ id[i]) * 1099511628211ULL;
  }
  return x;
}

// Open-addressed linear probe: O(1) expected.  TOMBSTONE keeps probe
// chains intact across deletions; probing stops at a true EMPTY.
Slot* find_slot(Header* h, const uint8_t* id) {
  Slot* tab = slots(h);
  uint64_t start = hash_id(id) % h->num_slots;
  for (uint32_t k = 0; k < h->num_slots; k++) {
    Slot* s = &tab[(start + k) % h->num_slots];
    if (s->state == SLOT_EMPTY) return nullptr;
    if (s->state != SLOT_TOMBSTONE && memcmp(s->id, id, kIdSize) == 0) {
      return s;
    }
  }
  return nullptr;
}

// Insert position for a new id: first tombstone on the probe path, else
// the terminating empty.  nullptr when the table is full.
Slot* insert_slot(Header* h, const uint8_t* id) {
  Slot* tab = slots(h);
  uint64_t start = hash_id(id) % h->num_slots;
  Slot* reuse = nullptr;
  for (uint32_t k = 0; k < h->num_slots; k++) {
    Slot* s = &tab[(start + k) % h->num_slots];
    if (s->state == SLOT_TOMBSTONE) {
      if (reuse == nullptr) reuse = s;
      continue;
    }
    if (s->state == SLOT_EMPTY) return reuse ? reuse : s;
  }
  return reuse;
}

void clear_slot(Header* h, Slot* s) {
  s->state = SLOT_TOMBSTONE;
  h->tombstones++;
  // Tombstone-heavy tables degrade probes; rehash in place when a
  // quarter of the table is dead.
  if (h->tombstones > h->num_slots / 4) {
    Slot* tab = slots(h);
    // Copy live slots out (bounded: kMaxRehash live entries on stack
    // per chunk would be complex; do a simple mark-and-reinsert using
    // the TOMBSTONE→EMPTY sweep + robin-hood-free reinsert loop).
    for (uint32_t i = 0; i < h->num_slots; i++) {
      if (tab[i].state == SLOT_TOMBSTONE) tab[i].state = SLOT_EMPTY;
    }
    h->tombstones = 0;
    // Reinsert every live slot whose probe position moved.
    for (uint32_t i = 0; i < h->num_slots; i++) {
      if (tab[i].state == SLOT_EMPTY) continue;
      Slot tmp = tab[i];
      tab[i].state = SLOT_EMPTY;
      Slot* dst = insert_slot(h, tmp.id);
      *dst = tmp;
    }
  }
}

void free_insert(Header* h, uint64_t offset, uint64_t size) {
  FreeBlock* fl = free_list(h);
  // Coalesce to fixpoint: merging can make the merged block adjacent to
  // further entries (eviction order is LRU, not address order).
  bool merged = true;
  while (merged) {
    merged = false;
    for (uint32_t i = 0; i < h->free_count; i++) {
      if (fl[i].offset + fl[i].size == offset) {
        offset = fl[i].offset;
        size += fl[i].size;
        fl[i] = fl[--h->free_count];
        merged = true;
        break;
      }
      if (offset + size == fl[i].offset) {
        size += fl[i].size;
        fl[i] = fl[--h->free_count];
        merged = true;
        break;
      }
    }
  }
  // A block ending at the high-water mark returns to the bump region.
  if (offset + size == h->bump) {
    h->bump = offset;
    return;
  }
  if (h->free_count < kFreeListCap) {
    fl[h->free_count++] = {offset, size};
  }
  // else: the block leaks until restart — bounded by kFreeListCap churn.
}

// Rebuild free list + counters from the slot table after a torn
// allocator mutation (robust-mutex recovery).  CREATED slots whose
// producer died are dropped; a LIVE producer's CREATED slot must
// survive — it is still writing through its pointer, and freeing the
// range would let a later create overlap it.
void rebuild_allocator(Header* h) {
  Slot* tab = slots(h);
  h->free_count = 0;
  h->bytes_used = 0;
  h->num_objects = 0;
  uint64_t max_end = 0;
  for (uint32_t i = 0; i < h->num_slots; i++) {
    Slot* s = &tab[i];
    // Acquire pairs with create's release commit: a state that reads
    // CREATED guarantees the extent fields below it are visible.
    uint32_t st = __atomic_load_n(&s->state, __ATOMIC_ACQUIRE);
    if (st == SLOT_CREATED && producer_dead(s)) {
      s->state = SLOT_TOMBSTONE;
      h->tombstones++;
      st = SLOT_TOMBSTONE;
    }
    if (st == SLOT_SEALED || st == SLOT_CREATED) {
      h->bytes_used += s->size;
      h->num_objects++;
      if (s->offset + s->size > max_end) max_end = s->offset + s->size;
    }
  }
  // Free space = everything below the live high-water mark that no
  // live (sealed or surviving-CREATED) slot covers.  Collect gaps by
  // sorting live extents.
  h->bump = max_end;
  // Insertion-sort live extents into a bounded stack array; fall back
  // to "no free list" (bump-only) if there are too many.
  constexpr uint32_t kMaxLive = 8192;
  static thread_local FreeBlock live[kMaxLive];
  uint32_t n = 0;
  for (uint32_t i = 0; i < h->num_slots && n < kMaxLive; i++) {
    if (tab[i].state == SLOT_SEALED || tab[i].state == SLOT_CREATED) {
      live[n++] = {tab[i].offset, tab[i].size};
    }
  }
  if (n < kMaxLive) {
    for (uint32_t i = 1; i < n; i++) {
      FreeBlock key = live[i];
      uint32_t j = i;
      while (j > 0 && live[j - 1].offset > key.offset) {
        live[j] = live[j - 1];
        j--;
      }
      live[j] = key;
    }
    uint64_t cursor = 0;
    for (uint32_t i = 0; i < n; i++) {
      if (live[i].offset > cursor && h->free_count < kFreeListCap) {
        free_list(h)[h->free_count++] = {cursor, live[i].offset - cursor};
      }
      cursor = live[i].offset + live[i].size;
    }
  }
}

// First-fit allocation from free list, then bump pointer.
int64_t alloc_block(Header* h, uint64_t size) {
  FreeBlock* fl = free_list(h);
  for (uint32_t i = 0; i < h->free_count; i++) {
    if (fl[i].size >= size) {
      uint64_t off = fl[i].offset;
      fl[i].offset += size;
      fl[i].size -= size;
      if (fl[i].size == 0) {
        fl[i] = fl[--h->free_count];
      }
      return static_cast<int64_t>(off);
    }
  }
  if (h->bump + size <= h->capacity) {
    uint64_t off = h->bump;
    h->bump += size;
    return static_cast<int64_t>(off);
  }
  return -1;
}

// Evict least-recently-used sealed refcount-0 objects until `size` fits.
// Parity: plasma EvictionPolicy::RequireSpace (eviction_policy.h).
// Return a slot's bytes to the allocator and tombstone it.  The single
// accounting path for every reclamation (evict, delete, abort, orphan
// reuse) — keeps bytes_used/num_objects in lockstep with the free map.
// May rehash the table (clear_slot): callers must hold no slot pointers.
void reclaim_slot(Header* h, Slot* s) {
  free_insert(h, s->offset, s->size);
  h->bytes_used -= s->size;
  h->num_objects--;
  clear_slot(h, s);
}

// Victim selection: dead-producer orphans FIRST — they are garbage,
// while a sealed victim is live cached data somebody may have to
// respill or refetch.  The kill(2) liveness probe runs only on CREATED
// slots, which are rare and short-lived.
Slot* pick_victim(Header* h) {
  Slot* tab = slots(h);
  for (uint32_t i = 0; i < h->num_slots; i++) {
    Slot* s = &tab[i];
    if (s->state == SLOT_CREATED && producer_dead(s)) return s;
  }
  Slot* victim = nullptr;
  for (uint32_t i = 0; i < h->num_slots; i++) {
    Slot* s = &tab[i];
    if (s->state == SLOT_SEALED && s->refcount == 0 &&
        (victim == nullptr || s->lru_tick < victim->lru_tick)) {
      victim = s;
    }
  }
  return victim;
}

// Reclaim one victim.  Orphan cleanup is not a cache eviction — only
// sealed victims count toward the evictions stat.
bool evict_one(Header* h) {
  Slot* victim = pick_victim(h);
  if (victim == nullptr) return false;
  if (victim->state == SLOT_SEALED) h->evictions++;
  reclaim_slot(h, victim);
  return true;
}

bool evict_for(Header* h, uint64_t size) {
  while (true) {
    FreeBlock* fl = free_list(h);
    bool fits = (h->bump + size <= h->capacity);
    for (uint32_t i = 0; !fits && i < h->free_count; i++) {
      fits = fl[i].size >= size;
    }
    if (fits) return true;
    if (!evict_one(h)) return false;
  }
}

}  // namespace

extern "C" {

// Create (owner=1) or open (owner=0) a store segment.
int shm_store_open(const char* name, uint64_t capacity, uint32_t num_slots,
                   int create, Store** out) {
  int fd;
  uint64_t meta = sizeof(Header) + sizeof(Slot) * (uint64_t)num_slots +
                  sizeof(FreeBlock) * (uint64_t)kFreeListCap;
  uint64_t total = meta + capacity;
  if (create) {
    shm_unlink(name);  // stale segment from a crashed run
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return -errno;
    if (ftruncate(fd, (off_t)total) != 0) {
      int e = errno;
      close(fd);
      shm_unlink(name);
      return -e;
    }
  } else {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return -errno;
    struct stat st;
    if (fstat(fd, &st) != 0) {
      int e = errno;
      close(fd);
      return -e;
    }
    total = (uint64_t)st.st_size;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    int e = errno;
    close(fd);
    return -e;
  }
  Header* h = static_cast<Header*>(mem);
  if (create) {
    memset(mem, 0, meta);
    h->magic = kMagic;
    h->capacity = capacity;
    h->data_start = meta;
    h->num_slots = num_slots;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
  } else if (h->magic != kMagic) {
    munmap(mem, total);
    close(fd);
    return -EINVAL;
  }
  Store* s = new Store();
  s->hdr = h;
  s->base = static_cast<uint8_t*>(mem);
  s->map_size = total;
  s->fd = fd;
  s->owner = create != 0;
  strncpy(s->name, name, sizeof(s->name) - 1);
  *out = s;
  return 0;
}

int shm_store_close(Store* s, int unlink_segment) {
  munmap(s->base, s->map_size);
  close(s->fd);
  if (unlink_segment) shm_unlink(s->name);
  delete s;
  return 0;
}

// Allocate an object; returns a writable pointer.  Fails with -EEXIST if
// the id is live, -ENOMEM if eviction can't make room, -ENOSPC if the
// slot table is full.
int shm_obj_create(Store* s, const uint8_t* id, uint64_t size, uint8_t** out) {
  Guard g(s->hdr);
  Header* h = s->hdr;
  Slot* prior = find_slot(h, id);
  if (prior != nullptr) {
    // A CREATED slot whose producer died is an orphan: reclaim it so
    // the id can be re-put (every other path — evict, delete, abort —
    // already treats it as reclaimable).
    if (prior->state != SLOT_CREATED || !producer_dead(prior)) {
      return -EEXIST;
    }
    reclaim_slot(h, prior);  // may rehash — no slot pointers held
  }
  if (size > h->capacity) return -ENOMEM;
  // Evict + allocate BEFORE picking the slot: eviction can trigger the
  // tombstone rehash inside clear_slot, which moves entries and would
  // invalidate (worse: repopulate) a slot pointer captured earlier.
  if (!evict_for(h, size)) return -ENOMEM;
  int64_t off = alloc_block(h, size);
  if (off < 0) return -ENOMEM;
  // A full slot table is also recoverable by eviction (a reclaimed
  // victim tombstones its slot); only fail -ENOSPC once nothing is
  // evictable.
  Slot* slot = insert_slot(h, id);
  while (slot == nullptr) {
    if (!evict_one(h)) {
      free_insert(h, (uint64_t)off, size);
      return -ENOSPC;
    }
    slot = insert_slot(h, id);
  }
  if (slot->state == SLOT_TOMBSTONE) h->tombstones--;
  // Populate every field BEFORE the state word: robust-mutex recovery
  // trusts offset/size/creator_pid of any slot whose state says
  // CREATED, so the state transition must be the commit point — a
  // release store, or the compiler/CPU may float it above the field
  // stores (a SIGKILL between the two would hand recovery a CREATED
  // slot with garbage extent fields).
  memcpy(slot->id, id, kIdSize);
  slot->refcount = 0;
  slot->offset = (uint64_t)off;
  slot->size = size;
  slot->lru_tick = ++h->lru_clock;
  slot->creator_pid = (uint64_t)getpid();
  __atomic_store_n(&slot->state, SLOT_CREATED, __ATOMIC_RELEASE);
  h->bytes_used += size;
  h->num_objects++;
  *out = arena(s) + off;
  return 0;
}

int shm_obj_seal(Store* s, const uint8_t* id) {
  Guard g(s->hdr);
  Slot* slot = find_slot(s->hdr, id);
  if (slot == nullptr) return -ENOENT;
  if (slot->state != SLOT_CREATED) return -EINVAL;
  slot->state = SLOT_SEALED;
  return 0;
}

// Pin + return a read pointer for a sealed object.  Caller must
// shm_obj_release when done reading.
int shm_obj_get(Store* s, const uint8_t* id, uint8_t** out, uint64_t* size) {
  Guard g(s->hdr);
  Slot* slot = find_slot(s->hdr, id);
  if (slot == nullptr) return -ENOENT;
  if (slot->state != SLOT_SEALED) return -EAGAIN;  // still being written
  slot->refcount++;
  slot->lru_tick = ++s->hdr->lru_clock;
  *out = arena(s) + slot->offset;
  *size = slot->size;
  return 0;
}

int shm_obj_release(Store* s, const uint8_t* id) {
  Guard g(s->hdr);
  Slot* slot = find_slot(s->hdr, id);
  if (slot == nullptr) return -ENOENT;
  if (slot->refcount > 0) slot->refcount--;
  return 0;
}

// Producer-side discard of an object created but not yet sealed (the
// plasma Abort counterpart): reclaims the arena block after a failed
// write.  Only CREATED slots qualify — sealed objects go through
// shm_obj_delete's refcount discipline — and only the creating process
// may abort (-EPERM otherwise): a peer aborting an in-progress slot
// would free arena bytes the producer is still writing through.
int shm_obj_abort(Store* s, const uint8_t* id) {
  Guard g(s->hdr);
  Header* h = s->hdr;
  Slot* slot = find_slot(h, id);
  if (slot == nullptr) return -ENOENT;
  if (slot->state != SLOT_CREATED) return -EINVAL;
  if (slot->creator_pid != (uint64_t)getpid() && !producer_dead(slot)) {
    return -EPERM;
  }
  reclaim_slot(h, slot);
  return 0;
}

int shm_obj_contains(Store* s, const uint8_t* id) {
  Guard g(s->hdr);
  Slot* slot = find_slot(s->hdr, id);
  return (slot != nullptr && slot->state == SLOT_SEALED) ? 1 : 0;
}

// Delete regardless of refcount==0 wait semantics: -EBUSY if referenced
// or still being written (an unsealed object belongs to its producer —
// parity with plasma's Abort-vs-Delete split: only the creating client
// may discard an object it has not sealed).
int shm_obj_delete(Store* s, const uint8_t* id) {
  Guard g(s->hdr);
  Header* h = s->hdr;
  Slot* slot = find_slot(h, id);
  if (slot == nullptr) return -ENOENT;
  if (slot->refcount > 0) return -EBUSY;
  // An unsealed object belongs to its producer while that producer is
  // alive; once it is dead the slot is an orphan anyone may reclaim.
  if (slot->state == SLOT_CREATED && !producer_dead(slot)) return -EBUSY;
  reclaim_slot(h, slot);
  return 0;
}

int shm_store_stats(Store* s, uint64_t* capacity, uint64_t* used,
                    uint64_t* num_objects, uint64_t* evictions) {
  Guard g(s->hdr);
  *capacity = s->hdr->capacity;
  *used = s->hdr->bytes_used;
  *num_objects = s->hdr->num_objects;
  *evictions = s->hdr->evictions;
  return 0;
}

}  // extern "C"
