// Sanitizer stress driver for the fixed-point cluster scheduler.
//
// Hammers rtsched_pick_and_acquire / try_acquire / release from many
// threads while other threads add and kill nodes, then checks the
// conservation invariant: once every acquisition is released, every
// node's available capacity equals its total.  Run under TSAN and
// ASAN/UBSAN by scripts/sanitize.sh (compiled together with
// scheduler.cc so sanitizers instrument every frame).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
void* rtsched_create(int64_t threshold_ppm);
void rtsched_destroy(void* h);
void rtsched_add_node(void* h, int64_t node, const int32_t* kinds,
                      const int64_t* caps, int n);
void rtsched_kill_node(void* h, int64_t node);
int64_t rtsched_pick_and_acquire(void* h, const int32_t* kinds,
                                 const int64_t* demand, int n, int strategy,
                                 const int64_t* candidates, int n_candidates);
int rtsched_try_acquire(void* h, int64_t node, const int32_t* kinds,
                        const int64_t* demand, int n);
void rtsched_release(void* h, int64_t node, const int32_t* kinds,
                     const int64_t* demand, int n);
int rtsched_cluster_can_fit(void* h, const int32_t* kinds,
                            const int64_t* demand, int n,
                            const int64_t* candidates, int n_candidates);
int64_t rtsched_available(void* h, int64_t node, int32_t kind);
int64_t rtsched_granularity();
}

namespace {

constexpr int kNodes = 12;
constexpr int32_t kCpu = 0;
constexpr int32_t kMem = 1;
std::atomic<long> g_errors{0};

struct Grant {
  int64_t node;
  int64_t cpu;
  int64_t mem;
};

void acquirer(void* h, int iters, int tid) {
  int strategy = tid & 1;
  unsigned seed = 0x85ebca6bu * (unsigned)(iters + 1) + 0xc2b2ae35u * (unsigned)tid;
  auto rnd = [&seed]() {
    seed = seed * 1664525u + 1013904223u;
    return seed;
  };
  std::vector<Grant> held;
  int32_t kinds[2] = {kCpu, kMem};
  for (int i = 0; i < iters; ++i) {
    int64_t demand[2] = {(int64_t)(1 + rnd() % 4) * 10000,
                         (int64_t)(rnd() % 3) * 10000};
    int64_t node = rtsched_pick_and_acquire(h, kinds, demand, 2, strategy,
                                            nullptr, -1);
    if (node >= 0) {
      held.push_back({node, demand[0], demand[1]});
    }
    // Release a random held grant half the time so pressure oscillates.
    if (!held.empty() && (rnd() & 1)) {
      size_t j = rnd() % held.size();
      int64_t d[2] = {held[j].cpu, held[j].mem};
      rtsched_release(h, held[j].node, kinds, d, 2);
      held[j] = held.back();
      held.pop_back();
    }
    if ((rnd() & 31) == 0) {
      rtsched_cluster_can_fit(h, kinds, demand, 2, nullptr, -1);
    }
  }
  for (auto& g : held) {
    int64_t d[2] = {g.cpu, g.mem};
    rtsched_release(h, g.node, kinds, d, 2);
  }
}

void churner(void* h, int iters) {
  // Kill and re-add the two highest-numbered nodes of the initial
  // cluster (10/11).  A killed node can still hold grants (release on a
  // dead node must stay safe) — that is exactly the raylet-death window
  // being checked.  These two are excluded from the final conservation
  // check: re-add resets available=total while grants are outstanding.
  int32_t kinds[2] = {kCpu, kMem};
  int64_t caps[2] = {32 * 10000, 64 * 10000};
  for (int i = 0; i < iters / 8; ++i) {
    int64_t node = kNodes - 2 + (i & 1);
    rtsched_kill_node(h, node);
    std::this_thread::yield();
    rtsched_add_node(h, node, kinds, caps, 2);
  }
}

}  // namespace

int main(int argc, char** argv) {
  int iters = argc > 1 ? atoi(argv[1]) : 20000;
  void* h = rtsched_create(-1);
  int32_t kinds[2] = {kCpu, kMem};
  int64_t caps[2] = {32 * 10000, 64 * 10000};
  for (int64_t n = 0; n < kNodes; ++n) {
    rtsched_add_node(h, n, kinds, caps, 2);
  }

  std::vector<std::thread> ts;
  for (int t = 0; t < 6; ++t) {
    ts.emplace_back(acquirer, h, iters, t);
  }
  ts.emplace_back(churner, h, iters);
  for (auto& t : ts) t.join();

  // Conservation: all grants released → available == total on the
  // stable nodes.  The churned nodes (kNodes-2, kNodes-1) are excluded:
  // re-adding resets them to full capacity while grants may still be
  // outstanding, so their ledgers legitimately drift.
  for (int64_t n = 0; n < kNodes - 2; ++n) {
    int64_t cpu = rtsched_available(h, n, kCpu);
    int64_t mem = rtsched_available(h, n, kMem);
    if (cpu != caps[0] || mem != caps[1]) {
      fprintf(stderr, "leak node=%lld cpu=%lld mem=%lld\n", (long long)n,
              (long long)cpu, (long long)mem);
      g_errors++;
    }
  }
  rtsched_destroy(h);
  fprintf(stderr, "done: errors=%ld\n", g_errors.load());
  return g_errors.load() == 0 ? 0 : 1;
}
