// Sanitizer stress driver for the shared-memory object store.
//
// Exercises the store's whole lifecycle concurrently — create/seal/get/
// release/delete with eviction pressure — from multiple threads and
// (fork-before-threads) multiple processes, so that TSAN can check the
// process-shared robust mutex discipline and ASAN/UBSAN the allocator
// arithmetic.  Parity intent: the reference runs its C++ under TSAN/ASAN
// CI jobs (ray: BUILD.bazel tsan/asan configs); this is the equivalent
// harness for our native layer.
//
// Built and run by scripts/sanitize.sh; compiled together with
// shm_store.cc (single TU link, no .so indirection, so sanitizers see
// every frame).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <sys/wait.h>
#include <unistd.h>

struct Store;
extern "C" {
int shm_store_open(const char* name, uint64_t capacity, uint32_t num_slots,
                   int create, Store** out);
int shm_store_close(Store* s, int unlink_segment);
int shm_obj_create(Store* s, const uint8_t* id, uint64_t size, uint8_t** out);
int shm_obj_seal(Store* s, const uint8_t* id);
int shm_obj_get(Store* s, const uint8_t* id, uint8_t** out, uint64_t* size);
int shm_obj_release(Store* s, const uint8_t* id);
int shm_obj_contains(Store* s, const uint8_t* id);
int shm_obj_delete(Store* s, const uint8_t* id);
int shm_store_stats(Store* s, uint64_t* capacity, uint64_t* used,
                    uint64_t* num_objects, uint64_t* evictions);
}

namespace {

constexpr int kIdSize = 32;
std::atomic<long> g_errors{0};

void make_id(uint8_t* id, int actor, int key) {
  memset(id, 0, kIdSize);
  memcpy(id, &actor, sizeof(actor));
  memcpy(id + sizeof(actor), &key, sizeof(key));
}

// One worker: loop create→write→seal→get→verify→release→(sometimes delete)
// over a small key space so threads collide on ids and eviction runs.
void worker(Store* s, int actor, int iters, int keyspace) {
  unsigned seed = 0x9e3779b9u * (unsigned)(actor + 1);
  auto rnd = [&seed]() {
    seed = seed * 1664525u + 1013904223u;
    return seed;
  };
  for (int i = 0; i < iters; ++i) {
    uint8_t id[kIdSize];
    make_id(id, actor % 4, (int)(rnd() % (unsigned)keyspace));
    uint64_t size = 256 + rnd() % (48 * 1024);
    uint8_t* w = nullptr;
    int rc = shm_obj_create(s, id, size, &w);
    if (rc == 0) {
      memset(w, (int)(size & 0xff), size);
      rc = shm_obj_seal(s, id);
      if (rc != 0) {
        // Nothing may touch our CREATED slot between create and seal:
        // eviction skips unsealed objects and delete returns -EBUSY on
        // them, so any nonzero rc is a store bug.
        fprintf(stderr, "seal rc=%d\n", rc);
        g_errors++;
      }
    } else if (rc != -EEXIST && rc != -ENOMEM && rc != -ENOSPC) {
      fprintf(stderr, "create rc=%d\n", rc);
      g_errors++;
    }
    uint8_t* r = nullptr;
    uint64_t rsize = 0;
    rc = shm_obj_get(s, id, &r, &rsize);
    if (rc == 0) {
      // Verify fill byte at both ends while pinned.
      uint8_t expect = (uint8_t)(rsize & 0xff);
      if (r[0] != expect || r[rsize - 1] != expect) {
        fprintf(stderr, "corrupt read size=%llu\n",
                (unsigned long long)rsize);
        g_errors++;
      }
      shm_obj_release(s, id);
    } else if (rc != -ENOENT && rc != -EAGAIN) {
      fprintf(stderr, "get rc=%d\n", rc);
      g_errors++;
    }
    if ((rnd() & 7) == 0) {
      rc = shm_obj_delete(s, id);
      if (rc != 0 && rc != -ENOENT && rc != -EBUSY) {
        fprintf(stderr, "delete rc=%d\n", rc);
        g_errors++;
      }
    }
  }
}

int run_threads(Store* s, int nthreads, int iters, int keyspace) {
  std::vector<std::thread> ts;
  for (int t = 0; t < nthreads; ++t) {
    ts.emplace_back(worker, s, t, iters, keyspace);
  }
  for (auto& t : ts) t.join();
  return g_errors.load() == 0 ? 0 : 1;
}

}  // namespace

int main(int argc, char** argv) {
  int iters = argc > 1 ? atoi(argv[1]) : 2000;
  int nprocs = argc > 2 ? atoi(argv[2]) : 2;
  const char* seg = "/raytpu_sanitize_stress";

  Store* s = nullptr;
  // 2 MiB arena + 512 slots: small enough that eviction and -ENOMEM
  // paths run constantly.
  int rc = shm_store_open(seg, 2u << 20, 512, /*create=*/1, &s);
  if (rc != 0) {
    fprintf(stderr, "open rc=%d\n", rc);
    return 2;
  }

  // Fork BEFORE any thread exists (TSAN requirement): each child opens
  // the same segment and runs its own thread pool, exercising the
  // process-shared mutex across address spaces.
  std::vector<pid_t> kids;
  for (int p = 0; p < nprocs; ++p) {
    pid_t pid = fork();
    if (pid == 0) {
      Store* cs = nullptr;
      rc = shm_store_open(seg, 0, 0, /*create=*/0, &cs);
      if (rc != 0) _exit(2);
      int bad = run_threads(cs, 4, iters, 64);
      shm_store_close(cs, 0);
      _exit(bad);
    }
    kids.push_back(pid);
  }

  int bad = run_threads(s, 4, iters, 64);

  for (pid_t pid : kids) {
    int st = 0;
    waitpid(pid, &st, 0);
    if (!WIFEXITED(st) || WEXITSTATUS(st) != 0) bad = 1;
  }

  uint64_t cap, used, n, ev;
  shm_store_stats(s, &cap, &used, &n, &ev);
  fprintf(stderr, "done: objects=%llu used=%llu evictions=%llu errors=%ld\n",
          (unsigned long long)n, (unsigned long long)used,
          (unsigned long long)ev, g_errors.load());
  shm_store_close(s, /*unlink=*/1);
  return bad;
}
