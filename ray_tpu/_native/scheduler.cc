// Native cluster-resource scheduler: fixed-point ledgers + policy picks.
//
// Parity: the reference's raylet scheduling core in C++ —
//   * FixedPoint resource arithmetic (ray: src/ray/common/scheduling/
//     fixed_point.h — int64 at 1e-4 granularity, no float drift),
//   * per-node available/total vectors (resource_instance_set.cc),
//   * the hybrid scheduling policy (raylet/scheduling/policy/
//     hybrid_scheduling_policy.h:28-46 — pack onto nodes below the
//     utilization threshold in stable order, else least-utilized),
//     plus SPREAD (spread_scheduling_policy.cc),
//   * atomic pick+acquire under one lock (the raylet's single-threaded
//     io_context discipline, here a mutex since callers are threads).
//
// Resource kinds are interned to dense ints by the Python side
// (parity: scheduling_ids.h string→int interning lives above the
// policy in the reference too).
//
// C ABI for ctypes (see ray_tpu/core/native_scheduler.py).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kGranularity = 10000;  // 1e-4 units, fixed_point.h parity

struct Node {
  std::vector<int64_t> total;      // indexed by interned resource kind
  std::vector<int64_t> available;
  bool alive = true;

  void ensure(size_t kinds) {
    if (total.size() < kinds) {
      total.resize(kinds, 0);
      available.resize(kinds, 0);
    }
  }

  // Max over kinds of used/total, in millionths (utilization * 1e6).
  int64_t utilization_ppm() const {
    int64_t worst = 0;
    for (size_t i = 0; i < total.size(); ++i) {
      if (total[i] > 0) {
        int64_t used = total[i] - available[i];
        int64_t ppm = used * 1000000 / total[i];
        if (ppm > worst) worst = ppm;
      }
    }
    return worst;
  }

  bool fits(const int64_t* demand, const int32_t* kinds, int n) const {
    for (int i = 0; i < n; ++i) {
      size_t k = static_cast<size_t>(kinds[i]);
      int64_t have = k < available.size() ? available[k] : 0;
      if (have < demand[i]) return false;
    }
    return true;
  }

  bool can_ever_fit(const int64_t* demand, const int32_t* kinds,
                    int n) const {
    for (int i = 0; i < n; ++i) {
      size_t k = static_cast<size_t>(kinds[i]);
      int64_t cap = k < total.size() ? total[k] : 0;
      if (cap < demand[i]) return false;
    }
    return true;
  }

  void acquire(const int64_t* demand, const int32_t* kinds, int n) {
    for (int i = 0; i < n; ++i) {
      size_t k = static_cast<size_t>(kinds[i]);
      // A kind this node never registered can pass fits() with a zero
      // demand — grow the vectors rather than writing out of bounds.
      ensure(k + 1);
      available[k] -= demand[i];
    }
  }

  void release(const int64_t* demand, const int32_t* kinds, int n) {
    for (int i = 0; i < n; ++i) {
      size_t k = static_cast<size_t>(kinds[i]);
      ensure(k + 1);
      available[k] += demand[i];
    }
  }
};

struct Scheduler {
  std::mutex mu;
  std::unordered_map<int64_t, Node> nodes;
  std::vector<int64_t> order;  // stable insertion order for hybrid pack
  int64_t threshold_ppm = 500000;  // hybrid spread threshold (0.5)
};

}  // namespace

extern "C" {

void* rtsched_create(int64_t threshold_ppm) {
  auto* s = new Scheduler();
  // 0 is a legal threshold ("never pack"); only negatives mean default.
  if (threshold_ppm >= 0) s->threshold_ppm = threshold_ppm;
  return s;
}

void rtsched_destroy(void* h) { delete static_cast<Scheduler*>(h); }

// Register / replace a node's capacity. kinds[i] is the interned id of
// caps[i]; caps are in fixed-point units (value * 1e4).
void rtsched_add_node(void* h, int64_t node, const int32_t* kinds,
                      const int64_t* caps, int n) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->nodes.find(node);
  if (it == s->nodes.end()) {
    s->order.push_back(node);
  }
  Node& nd = s->nodes[node];
  nd.alive = true;
  int32_t max_kind = -1;
  for (int i = 0; i < n; ++i) {
    if (kinds[i] > max_kind) max_kind = kinds[i];
  }
  nd.ensure(static_cast<size_t>(max_kind + 1));
  for (int i = 0; i < n; ++i) {
    size_t k = static_cast<size_t>(kinds[i]);
    nd.total[k] = caps[i];
    nd.available[k] = caps[i];
  }
}

void rtsched_kill_node(void* h, int64_t node) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->nodes.find(node);
  if (it != s->nodes.end()) it->second.alive = false;
}

// Strategy codes.
enum { STRAT_HYBRID = 0, STRAT_SPREAD = 1 };

// Atomically pick a node per the policy and acquire the demand on it.
// candidates: optional allow-list of node ids (affinity/label filtering
// done in Python); n_candidates < 0 means "all alive nodes".
// Returns the chosen node id, or -1 if nothing fits right now.
int64_t rtsched_pick_and_acquire(void* h, const int32_t* kinds,
                                 const int64_t* demand, int n,
                                 int strategy, const int64_t* candidates,
                                 int n_candidates) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);

  auto allowed = [&](int64_t id) {
    if (n_candidates < 0) return true;
    for (int i = 0; i < n_candidates; ++i) {
      if (candidates[i] == id) return true;
    }
    return false;
  };

  auto try_take = [&](int64_t id) -> bool {
    Node& nd = s->nodes[id];
    if (!nd.alive || !allowed(id) || !nd.fits(demand, kinds, n)) {
      return false;
    }
    nd.acquire(demand, kinds, n);
    return true;
  };

  if (strategy == STRAT_SPREAD) {
    // Least-utilized first (spread_scheduling_policy parity).
    int64_t best = -1;
    int64_t best_ppm = -1;
    for (int64_t id : s->order) {
      Node& nd = s->nodes[id];
      if (!nd.alive || !allowed(id) || !nd.fits(demand, kinds, n)) continue;
      int64_t ppm = nd.utilization_ppm();
      if (best == -1 || ppm < best_ppm) {
        best = id;
        best_ppm = ppm;
      }
    }
    if (best != -1) s->nodes[best].acquire(demand, kinds, n);
    return best;
  }

  // HYBRID: pack onto the first stable-order node below the threshold…
  for (int64_t id : s->order) {
    Node& nd = s->nodes[id];
    if (!nd.alive || !allowed(id)) continue;
    if (nd.utilization_ppm() < s->threshold_ppm && try_take(id)) return id;
  }
  // …else fall back to least-utilized that fits.
  int64_t best = -1;
  int64_t best_ppm = -1;
  for (int64_t id : s->order) {
    Node& nd = s->nodes[id];
    if (!nd.alive || !allowed(id) || !nd.fits(demand, kinds, n)) continue;
    int64_t ppm = nd.utilization_ppm();
    if (best == -1 || ppm < best_ppm) {
      best = id;
      best_ppm = ppm;
    }
  }
  if (best != -1) s->nodes[best].acquire(demand, kinds, n);
  return best;
}

// Direct acquire on a specific node (PG-bundle reservation path).
int rtsched_try_acquire(void* h, int64_t node, const int32_t* kinds,
                        const int64_t* demand, int n) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->nodes.find(node);
  if (it == s->nodes.end() || !it->second.alive ||
      !it->second.fits(demand, kinds, n)) {
    return 0;
  }
  it->second.acquire(demand, kinds, n);
  return 1;
}

void rtsched_release(void* h, int64_t node, const int32_t* kinds,
                     const int64_t* demand, int n) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->nodes.find(node);
  if (it != s->nodes.end()) it->second.release(demand, kinds, n);
}

// Feasibility anywhere (infeasible-task detection parity).
int rtsched_cluster_can_fit(void* h, const int32_t* kinds,
                            const int64_t* demand, int n,
                            const int64_t* candidates, int n_candidates) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  for (auto& [id, nd] : s->nodes) {
    if (!nd.alive) continue;
    if (n_candidates >= 0) {
      bool ok = false;
      for (int i = 0; i < n_candidates; ++i) {
        if (candidates[i] == id) { ok = true; break; }
      }
      if (!ok) continue;
    }
    if (nd.can_ever_fit(demand, kinds, n)) return 1;
  }
  return 0;
}

// Snapshot one node's (total, available) for a kind; returns -1 if the
// node is unknown.  Used for introspection/tests.
int64_t rtsched_available(void* h, int64_t node, int32_t kind) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->nodes.find(node);
  if (it == s->nodes.end()) return -1;
  auto& av = it->second.available;
  size_t k = static_cast<size_t>(kind);
  return k < av.size() ? av[k] : 0;
}

int64_t rtsched_utilization_ppm(void* h, int64_t node) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->nodes.find(node);
  if (it == s->nodes.end()) return -1;
  return it->second.utilization_ppm();
}

int64_t rtsched_granularity() { return kGranularity; }

}  // extern "C"
