"""Native (C++) components, compiled on first use.

Parity: the reference builds its C++ core with Bazel into a Cython
extension (ray: python/setup.py → bazel → _raylet.pyx); here each native
component is a small C ABI library built with g++ and bound via ctypes
— no build step at install time, no toolchain beyond a C++ compiler.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import List, Optional

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_NATIVE_DIR, "build")
_build_lock = threading.Lock()


def build_library(source: str, libname: str,
                  extra_flags: Optional[List[str]] = None) -> str:
    """Compile ``source`` (relative to this dir) into build/<libname>.so,
    rebuilding when the source is newer.  Returns the .so path."""
    src = os.path.join(_NATIVE_DIR, source)
    out = os.path.join(_BUILD_DIR, libname + ".so")
    with _build_lock:
        if (os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", out, src,
            "-lpthread", "-lrt",
        ] + (extra_flags or [])
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{proc.stderr}"
            )
    return out
