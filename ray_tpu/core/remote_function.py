"""@remote functions.

Parity with the reference's RemoteFunction
(ray: python/ray/remote_function.py:40; `_remote` :257) and the options
validation table (ray: python/ray/_private/ray_option_utils.py):
``f.remote(*args)`` submits through the runtime, ``f.options(...)``
returns a shallow copy with overridden options.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.runtime import TaskOptions

_VALID_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "num_returns", "max_retries",
    "name", "scheduling_strategy", "placement_group",
    "placement_bundle_index", "runtime_env",
}


def _make_task_options(defaults: Dict[str, Any], overrides: Dict[str, Any]
                       ) -> TaskOptions:
    merged = {**defaults, **overrides}
    bad = set(merged) - _VALID_OPTIONS
    if bad:
        raise ValueError(
            f"invalid option(s) {sorted(bad)}; valid: {sorted(_VALID_OPTIONS)}"
        )
    return TaskOptions(**merged)


class RemoteFunction:
    def __init__(self, fn: Callable, **default_options):
        if not callable(fn):
            raise TypeError("@remote must wrap a callable")
        self._fn = fn
        self._default_options = default_options
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__!r} cannot be called "
            f"directly — use {self._fn.__name__}.remote(...)"
        )

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        return self._submit(args, kwargs, {})

    def bind(self, *args, **kwargs):
        """Lazy DAG node (parity: ray DAGNode bind, dag/function_node.py)."""
        from ray_tpu.util.dag import bind_function

        return bind_function(self, *args, **kwargs)

    def options(self, **overrides) -> "_BoundOptions":
        _make_task_options(self._default_options, overrides)  # validate now
        return _BoundOptions(self, overrides)

    def _submit(self, args, kwargs, overrides):
        from ray_tpu.core import api

        if overrides:
            opts = _make_task_options(self._default_options, overrides)
        else:
            # Hot path: the default options never change — build once
            # (submit_task treats TaskOptions as read-only).
            opts = self.__dict__.get("_cached_opts")
            if opts is None:
                opts = _make_task_options(self._default_options, {})
                self.__dict__["_cached_opts"] = opts
        refs = api.runtime().submit_task(self._fn, args, kwargs, opts)
        if opts.num_returns == "streaming":
            return refs  # an ObjectRefGenerator
        return refs[0] if opts.num_returns == 1 else refs

    @property
    def underlying(self) -> Callable:
        return self._fn


class _BoundOptions:
    def __init__(self, rf: RemoteFunction, overrides: Dict[str, Any]):
        self._rf = rf
        self._overrides = overrides

    def remote(self, *args, **kwargs):
        return self._rf._submit(args, kwargs, self._overrides)
