"""Node memory monitor + OOM worker-killing policies.

Parity: the reference's ``MemoryMonitor``
(ray: src/ray/common/memory_monitor.h:52 — cgroup-aware used/total
sampling on a timer, threshold callback) and the raylet's policy-based
OOM killer (ray: src/ray/raylet/worker_killing_policy.cc,
worker_killing_policy_retriable_fifo.cc,
worker_killing_policy_group_by_owner.cc): when the node crosses the
memory threshold, kill retriable work first — grouped by owner so one
greedy job pays, and LIFO within a group so the shortest-lived work is
sacrificed.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple


def get_system_memory_bytes() -> Tuple[int, int]:
    """(used, total) bytes — cgroup v2 limit if present, else
    /proc/meminfo (parity: MemoryMonitor::GetMemoryBytes cgroup-first)."""
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw != "max":
            total = int(raw)
            with open("/sys/fs/cgroup/memory.current") as f:
                used = int(f.read().strip())
            return used, total
    except OSError:
        pass
    info = {}
    with open("/proc/meminfo") as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                info[parts[0].rstrip(":")] = int(parts[1]) * 1024
    total = info.get("MemTotal", 0)
    avail = info.get("MemAvailable", info.get("MemFree", 0))
    return total - avail, total


class MemoryMonitor:
    """Polls memory usage on a timer thread; fires ``callback(used,
    total)`` whenever usage exceeds ``usage_threshold`` (parity:
    MemoryMonitor's monitor callback driving the OOM killer)."""

    def __init__(self, usage_threshold: float = 0.95,
                 check_interval_s: float = 0.25,
                 callback: Optional[Callable[[int, int], None]] = None,
                 usage_fn: Callable[[], Tuple[int, int]] =
                 get_system_memory_bytes):
        self.usage_threshold = usage_threshold
        self.check_interval_s = check_interval_s
        self.callback = callback
        self.usage_fn = usage_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def is_over_threshold(self) -> bool:
        used, total = self.usage_fn()
        return total > 0 and used / total > self.usage_threshold

    def start(self) -> "MemoryMonitor":
        self._thread = threading.Thread(
            target=self._loop, name="memory-monitor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            used, total = self.usage_fn()
            if total > 0 and used / total > self.usage_threshold \
                    and self.callback is not None:
                self.callback(used, total)


@dataclasses.dataclass
class KillCandidate:
    """One killable unit of work (parity: the raylet's view of a worker:
    its task's retriability, start time, and owning job/actor)."""

    id: str
    retriable: bool
    start_time: float
    owner_id: str = ""


def retriable_fifo_policy(candidates: Sequence[KillCandidate]
                          ) -> Optional[KillCandidate]:
    """Retriable tasks first, oldest first (parity:
    worker_killing_policy_retriable_fifo.cc — FIFO among retriable,
    then FIFO among the rest)."""
    if not candidates:
        return None
    return min(candidates,
               key=lambda c: (not c.retriable, c.start_time))


def group_by_owner_policy(candidates: Sequence[KillCandidate]
                          ) -> Optional[KillCandidate]:
    """Group by owner; prefer a retriable group, break ties by group
    size (largest pays), kill the newest member so the group loses the
    least progress (parity: worker_killing_policy_group_by_owner.cc)."""
    if not candidates:
        return None
    groups: dict = {}
    for c in candidates:
        groups.setdefault((c.retriable, c.owner_id), []).append(c)
    # Sort groups: retriable first, then larger groups first.
    (_, _), members = sorted(
        groups.items(),
        key=lambda kv: (not kv[0][0], -len(kv[1])),
    )[0]
    return max(members, key=lambda c: c.start_time)


def process_rss_bytes(pid: Optional[int] = None) -> int:
    """Resident set size of a process (parity: MemoryMonitor::
    GetProcessMemoryBytes reading /proc/<pid>/smaps_rollup or statm)."""
    pid = pid or os.getpid()
    try:
        with open(f"/proc/{pid}/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return 0


class OomKiller:
    """Wires a MemoryMonitor to a kill policy over the runtime's
    restartable actors (the killable unit in this runtime — thread-based
    tasks can't be safely interrupted, matching the reference's rule of
    only killing *retriable* work).  On pressure: kill one candidate per
    grace period; its max_restarts budget restarts it when memory frees
    (parity: raylet WorkerKillingPolicy + actor restart FSM)."""

    def __init__(self, runtime, *, usage_threshold: float = 0.95,
                 policy=group_by_owner_policy,
                 check_interval_s: float = 0.25,
                 grace_period_s: float = 1.0,
                 usage_fn: Callable[[], Tuple[int, int]] =
                 get_system_memory_bytes):
        self.runtime = runtime
        self.policy = policy
        self.grace_period_s = grace_period_s
        self.kills: List[str] = []
        self._last_kill = 0.0
        self.monitor = MemoryMonitor(
            usage_threshold=usage_threshold,
            check_interval_s=check_interval_s,
            callback=self._on_pressure, usage_fn=usage_fn,
        )

    def start(self) -> "OomKiller":
        self.monitor.start()
        return self

    def stop(self) -> None:
        self.monitor.stop()

    def _on_pressure(self, used: int, total: int) -> None:
        now = time.monotonic()
        if now - self._last_kill < self.grace_period_s:
            return
        with self.runtime._lock:
            shells = [s for s in self.runtime._actors.values()
                      if not s.dead]
        candidates = [
            KillCandidate(
                id=s.actor_id.hex(),
                retriable=s.restarts_left > 0,
                start_time=getattr(s, "_start_ts", 0.0),
                owner_id=s.runtime.job_id.hex(),
            )
            for s in shells
        ]
        victim = self.policy(candidates)
        if victim is None:
            return
        self._last_kill = now
        self.kills.append(victim.id)
        for s in shells:
            if s.actor_id.hex() == victim.id:
                # no_restart=False: the actor's own max_restarts budget
                # decides whether it comes back (parity: OOM-killed
                # retriable tasks are retried).
                s.kill(no_restart=False)
                break
