"""Cluster runtime: tasks, actors, objects over logical nodes in one process.

Semantics-first parity with the reference's core: dependency-aware task
dispatch (ray: raylet/local_task_manager.cc WaitForTaskArgsRequests /
DispatchScheduledTasksToWorkers), two-phase cluster scheduling with the
hybrid pack-then-spread policy (raylet/scheduling/cluster_task_manager.cc:44,
policy/hybrid_scheduling_policy.h:28-50), logical resource accounting
(common/scheduling/resource_instance_set.cc), per-actor ordered execution
queues (core_worker/transport/actor_scheduling_queue.cc), error capture +
retries (core_worker/task_manager.h max_retries), named actors (gcs actor
directory), placement-group bundle reservation
(gcs/gcs_server/gcs_placement_group_scheduler.cc), and node membership +
death propagation (gcs/gcs_server/gcs_node_manager.cc).

The cluster is simulated as N logical nodes inside one process — the same
trick the reference uses for multi-node tests (python/ray/cluster_utils.py
Cluster runs N raylets locally).  Libraries only ever see the api module,
so they run unchanged when workers move behind a process/RPC boundary.
"""

from __future__ import annotations

import collections as _collections
import contextlib
import dataclasses
import inspect as _inspect
import itertools
import threading
import time
import queue as _queue
import re as _re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core import events as _ev
from ray_tpu.core.exceptions import (
    ActorDiedError,
    TaskCancelledError,
    TaskError,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.placement_group import (
    Bundle,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
)
from ray_tpu.core.store import LocalObjectStore
from ray_tpu.utils.config import get_config
from ray_tpu.utils.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
)

_tracing_mod = None

# Gloo emits one "[Gloo] Rank N is connected to M peer ranks ..." line
# per rank per rendezvous — O(ranks^2) console spam on multi-process
# CPU dryruns.  Matched lines are kept in the LogBuffer but skipped by
# the driver echo (ingest_logs).
_GLOO_CONNECT_RE = _re.compile(
    r"\[Gloo\]\s+Rank\s+\d+\s+is\s+connected\s+to\s+\d+\s+peer\s+ranks")


def _tracing():
    """Cycle-safe cached import of ray_tpu.util.tracing (ray_tpu.util's
    __init__ imports back into core, so a top-level import here would
    be circular)."""
    global _tracing_mod
    if _tracing_mod is None:
        from ray_tpu.util import tracing

        _tracing_mod = tracing
    return _tracing_mod


@dataclasses.dataclass
class TaskOptions:
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    num_returns: int = 1
    max_retries: int = 0
    name: str = ""
    scheduling_strategy: Any = "DEFAULT"
    placement_group: Any = None
    placement_bundle_index: int = -1
    runtime_env: Any = None

    def resource_demand(self) -> Dict[str, float]:
        demand = dict(self.resources)
        if self.num_cpus:
            demand["CPU"] = demand.get("CPU", 0) + self.num_cpus
        if self.num_tpus:
            demand["TPU"] = demand.get("TPU", 0) + self.num_tpus
        return demand

    def effective_strategy(self) -> Any:
        if self.placement_group is not None:
            return PlacementGroupSchedulingStrategy(
                self.placement_group, self.placement_bundle_index
            )
        return self.scheduling_strategy


@dataclasses.dataclass
class ActorOptions:
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    name: Optional[str] = None
    get_if_exists: bool = False
    max_restarts: int = 0
    max_concurrency: int = 1
    # Named concurrency groups: group → max concurrent calls.  Methods
    # route via @method(concurrency_group=...) or per-call .options();
    # each group executes independently, so a slow group cannot starve
    # another (parity: ray concurrency groups,
    # core_worker/transport/concurrency_group_manager.cc).
    concurrency_groups: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    # Out-of-order execution: a queued call whose ObjectRef args are
    # not ready yet does not block later calls (parity:
    # out_of_order_actor_submit_queue.cc).  Ordering guarantees are
    # forfeited, as in the reference.
    execute_out_of_order: bool = False
    lifetime: Optional[str] = None  # None | "detached"
    scheduling_strategy: Any = "DEFAULT"
    placement_group: Any = None
    placement_bundle_index: int = -1
    runtime_env: Any = None

    def resource_demand(self) -> Dict[str, float]:
        demand = dict(self.resources)
        if self.num_cpus:
            demand["CPU"] = demand.get("CPU", 0) + self.num_cpus
        if self.num_tpus:
            demand["TPU"] = demand.get("TPU", 0) + self.num_tpus
        return demand

    def effective_strategy(self) -> Any:
        if self.placement_group is not None:
            return PlacementGroupSchedulingStrategy(
                self.placement_group, self.placement_bundle_index
            )
        return self.scheduling_strategy


class ResourcePool:
    """Logical resource ledger (parity: NodeResourceInstanceSet).

    When the native scheduler built (ray_tpu/_native/scheduler.cc), the
    ledger lives in C++ fixed-point arithmetic — acquire/release/
    utilization forward there (parity: the raylet's C++ resource core).
    Pure-Python fallback when no C++ toolchain is available."""

    def __init__(self, total: Dict[str, float], native=None):
        self._lock = threading.Lock()
        self.total = dict(total)
        self._avail = dict(total)
        # native = (NativeClusterScheduler, node_int_id) or None
        self._native = native

    @property
    def available(self) -> Dict[str, float]:
        if self._native is not None:
            sched, nid = self._native
            return {k: sched.available(nid, k) for k in self.total}
        return self._avail

    def can_fit(self, demand: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0) >= v for k, v in demand.items())

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        if self._native is not None:
            sched, nid = self._native
            return sched.try_acquire(nid, demand)
        with self._lock:
            if all(self._avail.get(k, 0) >= v - 1e-9 for k, v in demand.items()):
                for k, v in demand.items():
                    self._avail[k] = self._avail.get(k, 0) - v
                return True
            return False

    def release(self, demand: Dict[str, float]) -> None:
        if self._native is not None:
            sched, nid = self._native
            sched.release(nid, demand)
            return
        with self._lock:
            for k, v in demand.items():
                self._avail[k] = self._avail.get(k, 0) + v

    def utilization(self) -> float:
        """Max over resource kinds of used/total (0 = idle, 1 = full)."""
        if self._native is not None:
            sched, nid = self._native
            return sched.utilization(nid)
        with self._lock:
            worst = 0.0
            for k, tot in self.total.items():
                if tot > 0:
                    worst = max(worst, (tot - self._avail.get(k, 0)) / tot)
            return worst


class NodeState:
    """One logical node: resources + labels + liveness
    (parity: GcsNodeManager's node table entry + raylet resource view)."""

    def __init__(self, node_id: NodeID, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 native=None, int_id: int = -1):
        self.node_id = node_id
        self.int_id = int_id  # dense id for the native scheduler
        self.pool = ResourcePool(resources, native=native)
        self.labels = dict(labels or {})
        self.alive = True
        self.actor_ids: set = set()
        # Remote node daemon handle (ray_tpu.core.node_daemon
        # RemoteNodeAgent) — None for the head's local node and for
        # logical test nodes.  When set, tasks/actors allocated here
        # dispatch over the daemon's channel to ITS worker pool, and
        # the daemon's object-plane address is ``addr``.
        self.agent = None
        self.addr: Optional[Tuple[str, int]] = None

    def matches_labels(self, required: Dict[str, str]) -> bool:
        return all(self.labels.get(k) == v for k, v in required.items())


@dataclasses.dataclass
class _Allocation:
    """Where a task/actor's resources came from, for symmetric release."""

    node: Optional[NodeState]
    bundle: Optional[Bundle]
    demand: Dict[str, float]

    def release(self):
        if self.bundle is not None:
            # node_id must be read under the bundle lock so we can't race
            # remove_placement_group between its ledger-zeroing and its
            # node_id reset (which would credit a dead ledger).
            with self.bundle.lock:
                still_ours = (self.node is not None
                              and self.bundle.node_id == self.node.node_id)
                if still_ours:
                    for k, v in self.demand.items():
                        self.bundle.available[k] = \
                            self.bundle.available.get(k, 0) + v
            if still_ours:
                pass
            elif self.node is not None:
                # The bundle moved away (PG removed, or relocated after a
                # node death).  The in-use portion was never returned to
                # the node when that happened — return it now.  If the
                # node is dead its pool is inert, so this is harmless.
                self.node.pool.release(self.demand)
        elif self.node is not None:
            self.node.pool.release(self.demand)


@dataclasses.dataclass
class _PendingTask:
    fn: Callable
    args: tuple
    kwargs: dict
    options: TaskOptions
    return_ids: List[ObjectID]
    retries_left: int
    task_id: TaskID
    function_name: str
    streaming: bool = False
    on_done: Optional[Callable[[], None]] = None
    trace_ctx: Optional[Dict[str, str]] = None
    # Set by ray_tpu.cancel: never (re)dispatch, never retry (parity:
    # TaskSpec cancellation flag checked in _raylet.pyx:1806).
    cancelled: bool = False
    # Unsatisfied dependency oids while parked in the waiting index
    # (parity: DependencyManager's per-task unfulfilled set).
    waiting_on: Optional[set] = None
    # Resource demand, computed once at submission (hot path).
    demand: Optional[Dict[str, float]] = None
    # Explicit dependency list (nested submissions ship WireRef args +
    # a deps list instead of live handles — parity: TaskSpec's
    # dependency ids).  None → collect ObjectRefs from args/kwargs.
    arg_oids: Optional[List[ObjectID]] = None
    # Head-side handles pinning explicit deps (same lifetime as the
    # handles that live inside args on the normal path).
    arg_refs: Optional[list] = None
    # Pickled (fn, args, kwargs) of a daemon-dispatched task; hydrated
    # lazily only if the head must re-run it (retry, reconstruction).
    spec_blob: Optional[bytes] = None


class _CachedThreadPool:
    """Task-execution threads, pooled and reused (parity: the raylet's
    WorkerPool keeping warm workers instead of forking per task,
    worker_pool.h:156 — here for thread mode).  Unbounded on purpose:
    tasks may block arbitrarily long (nested ray.get), so a bounded
    pool would deadlock; idle threads expire instead."""

    def __init__(self, idle_timeout: float = 2.0, name: str = "task-exec"):
        import collections as _c

        self._cv = threading.Condition()
        self._work: "_c.deque" = _c.deque()
        self._idle = 0
        self._timeout = idle_timeout
        self._name = name
        self._seq = itertools.count()
        self._closed = False

    def submit(self, fn: Callable[[], None]) -> None:
        spawn = False
        with self._cv:
            if self._closed:
                return
            self._work.append(fn)
            if self._idle > 0:
                self._cv.notify()
            if len(self._work) > self._idle:
                spawn = True
        if spawn:
            threading.Thread(
                target=self._worker, daemon=True,
                name=f"{self._name}-{next(self._seq)}",
            ).start()

    def _worker(self) -> None:
        import time as _time

        while True:
            with self._cv:
                deadline = _time.monotonic() + self._timeout
                self._idle += 1
                while not self._work:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0 or self._closed:
                        self._idle -= 1
                        return
                    self._cv.wait(remaining)
                self._idle -= 1
                fn = self._work.popleft()
            try:
                fn()
            except BaseException:
                pass  # task bodies seal their own errors

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._work.clear()
            self._cv.notify_all()


# Returned by _execute_item when completion happens later on the actor's
# event loop (async method): the serve loop must not record FINISHED.
_ASYNC_DEFERRED = object()


def _collect_arg_oids(args: tuple, kwargs: dict) -> List[ObjectID]:
    """Top-level ObjectRef dependencies of one actor call (the same
    top-level contract as resolve_args / the dependency index)."""
    from ray_tpu.core.object_ref import ObjectRef as _OR

    return [v.id for v in list(args) + list(kwargs.values())
            if isinstance(v, _OR)]


from ray_tpu.utils.interrupt import (
    async_raise as _async_raise,
    clear_async_exc as _clear_async_exc,
)


class _ActorShell:
    """Server side of one actor: instance + execution thread(s).

    max_concurrency == 1 (default): one thread drains the queue in
    submission order (parity: ActorSchedulingQueue ordering guarantee).
    max_concurrency > 1: a pool of threads drains the same queue and
    ordering is NOT guaranteed (parity: threaded actors via
    BoundedExecutor, core_worker/transport/thread_pool.cc)."""

    def __init__(self, runtime: "LocalRuntime", actor_id: ActorID, cls: type,
                 args: tuple, kwargs: dict, options: ActorOptions,
                 creation_oid: ObjectID, allocation: _Allocation):
        self.runtime = runtime
        self.actor_id = actor_id
        self.cls = cls
        self.init_args = args
        self.init_kwargs = kwargs
        self.options = options
        self.allocation = allocation
        self.instance: Any = None
        self.dead = False
        self.death_reason = ""
        self.no_restart = False  # set by an explicit kill(no_restart=True)
        self.restarts_left = options.max_restarts
        self.queue: _queue.Queue = _queue.Queue()
        # Named concurrency groups: each gets its own queue + thread
        # pool, so groups execute independently (parity:
        # concurrency_group_manager.cc — one BoundedExecutor per group).
        self._group_queues: Dict[str, _queue.Queue] = {
            g: _queue.Queue() for g in (options.concurrency_groups or ())
        }
        self._creation_oid = creation_oid
        self.thread: Optional[threading.Thread] = None
        # Restart counter for per-attempt task events (parity: each
        # restart is a distinct attempt of the creation task).
        self.creation_attempt = -1
        # Cancellation bookkeeping (parity: actor task cancel via the
        # scheduling queue / asyncio task cancel).
        from ray_tpu.core.refcount import TombstoneSet

        self._cancel_lock = threading.Lock()
        self._cancelled = TombstoneSet(1024)  # cancelled-before-run ids
        self._running_sync: Dict[TaskID, Any] = {}  # id → thread ident
        self._inflight_async: Dict[TaskID, Any] = {}  # id → (fut, oids)
        # Async actors: one event loop thread per actor; N method calls
        # interleave as coroutines on it (parity: boost::fibers async
        # actors, core_worker/transport/fiber.h:55).
        self._loop = None
        self._loop_thread: Optional[threading.Thread] = None
        self._async_sem = None
        self._async_group_sems: Dict[str, Any] = {}
        # Orders "dead/drained check + queue.put" against kill/_drain so
        # a racing submit (esp. a dep-blocked out-of-order call whose
        # wait spans the death) can't land in a queue nothing drains.
        self._submit_gate = threading.Lock()
        self._drained = False
        # Out-of-order mode: dep-blocked calls park here; ONE dispatcher
        # thread enqueues them as their deps seal.
        self._ooo_pending: List[Any] = []
        self._ooo_thread: Optional[threading.Thread] = None

    @property
    def node_id(self) -> Optional[NodeID]:
        return self.allocation.node.node_id if self.allocation.node else None

    def start(self):
        """Called after the runtime has registered the actor, so death
        bookkeeping always sees a registered actor."""
        import time as _time

        # Age for OOM kill policies (reset per (re)start — parity: the
        # policies rank by the running task's start time).
        self._start_ts = _time.monotonic()
        self.thread = threading.Thread(
            target=self._run, name=f"actor-{self.actor_id.hex()[:8]}",
            daemon=True,
        )
        self.thread.start()

    def _construct(self):
        if self.options.runtime_env:
            from ray_tpu.runtime_env import materialize

            self._env_ctx = materialize(self.options.runtime_env)
            with self._env_ctx.applied():
                self.instance = self.cls(*self.init_args, **self.init_kwargs)
        else:
            self._env_ctx = None
            self.instance = self.cls(*self.init_args, **self.init_kwargs)

    def _run(self):
        # Actor creation is the first "task" (parity: actor creation task).
        ev = self.runtime.events
        ctid = getattr(self, "creation_task_id", None)
        self.creation_attempt += 1
        attempt = self.creation_attempt
        if ctid is not None:
            ev.record(ctid.hex(), _ev.RUNNING, attempt=attempt,
                      name=f"{self.cls.__name__}.__init__",
                      type=_ev.ACTOR_CREATION_TASK,
                      actor_id=self.actor_id.hex(),
                      node_id=(self.node_id.hex() if self.node_id else None),
                      worker=threading.current_thread().name)
        try:
            self._construct()
            self.runtime.store.put_value(self._creation_oid, None)
            if ctid is not None:
                ev.record(ctid.hex(), _ev.FINISHED, attempt=attempt)
        except BaseException as e:
            self.dead = True
            self.death_reason = f"creation failed: {e!r}"
            if ctid is not None:
                ev.record(ctid.hex(), _ev.FAILED, attempt=attempt,
                          error_message=repr(e))
            err = ActorDiedError(repr(self.cls), self.death_reason)
            self.runtime.store.put_error(self._creation_oid, err)
            # Methods queued while __init__ was still running must fail,
            # not hang (submissions after death are rejected in submit()).
            self._drain(err)
            self.runtime._on_actor_death(self)
            return
        # max_concurrency > 1: a pool of threads drains the same queue, so
        # blocking calls (long-polls, slow requests) don't serialize
        # (parity: threaded actors via BoundedExecutor,
        # core_worker/transport/thread_pool.cc — ordering is only
        # guaranteed for max_concurrency == 1, as in the reference).
        n = max(1, int(self.options.max_concurrency))
        extra = [
            threading.Thread(
                target=self._serve_loop, daemon=True,
                name=f"actor-{self.actor_id.hex()[:8]}-c{i + 1}",
            )
            for i in range(n - 1)
        ]
        # One pool per named concurrency group, sized by its declared
        # limit — a stalled group never borrows (or blocks) another
        # group's threads.
        for gname, gsize in (self.options.concurrency_groups or {}).items():
            extra += [
                threading.Thread(
                    target=self._serve_loop,
                    args=(self._group_queues[gname],), daemon=True,
                    name=f"actor-{self.actor_id.hex()[:8]}-{gname}{i}",
                )
                for i in range(max(1, int(gsize)))
            ]
        for t in extra:
            t.start()
        self._serve_loop()
        for t in extra:
            t.join()
        self._drain(ActorDiedError(repr(self.cls), self.death_reason or "killed"))
        self.runtime._on_actor_death(self)

    def _serve_loop(self, queue: Optional[_queue.Queue] = None):
        queue = queue if queue is not None else self.queue
        while True:
            item = queue.get()
            if item is None:  # kill signal — re-post so sibling threads stop
                queue.put(None)
                return
            method_name, args, kwargs, return_ids, num_returns = item[:5]
            task_id = item[5] if len(item) > 5 else None
            trace_ctx = item[6] if len(item) > 6 else None
            cgroup = item[7] if len(item) > 7 else None
            task_hex = task_id.hex() if task_id is not None else None
            ev = self.runtime.events
            qname = f"{self.cls.__name__}.{method_name}"
            if task_id is not None:
                with self._cancel_lock:
                    was_cancelled = task_id in self._cancelled
                if was_cancelled:
                    # Cancelled while queued: never runs (parity: the
                    # scheduling queue drops cancelled actor tasks).
                    self.runtime._seal_cancelled(
                        task_id, return_ids, num_returns == "streaming")
                    if task_hex:
                        ev.record(task_hex, _ev.FAILED,
                                  error_message="cancelled")
                    continue
            if task_hex:
                ev.record(task_hex, _ev.RUNNING, name=qname,
                          type=_ev.ACTOR_TASK, actor_id=self.actor_id.hex(),
                          node_id=(self.node_id.hex() if self.node_id
                                   else None),
                          worker=self._worker_label())
            try:
                outcome = self._execute_item(qname, method_name, args, kwargs,
                                             return_ids, num_returns, task_id,
                                             trace_ctx, task_hex,
                                             cgroup=cgroup)
                if task_hex and outcome is not _ASYNC_DEFERRED:
                    ev.record(task_hex, _ev.FINISHED)
            except BaseException as e:
                if task_hex:
                    ev.record(task_hex, _ev.FAILED, error_message=repr(e))
                err = (e if isinstance(e, TaskCancelledError)
                       else self._item_error(qname, e))
                for oid in return_ids:
                    self.runtime.store.put_error(oid, err)
                if num_returns == "streaming" and task_id is not None:
                    # Seal at the first unsealed index (a worker may
                    # already have produced a prefix of the stream) so
                    # the consumer's next() unblocks with the error.
                    self.runtime._seal_stream_failure(task_id, err)
                if self._after_item_error(e):
                    return

    def _worker_label(self) -> str:
        return threading.current_thread().name

    def _execute_item(self, qname, method_name, args, kwargs, return_ids,
                      num_returns, task_id, trace_ctx, task_hex,
                      cgroup=None):
        """Run one dequeued method call; overridden by the process
        shell to push it to the actor's worker process."""
        resolved_args, resolved_kwargs = self.runtime.resolve_args(
            args, kwargs
        )
        method = getattr(self.instance, method_name)
        if _inspect.iscoroutinefunction(method) and num_returns != "streaming":
            # Async actor path: schedule on the actor's event loop and
            # return immediately — the serve loop moves to the next
            # item, so N awaits interleave (parity: fiber.h async
            # actors).  Completion seals results from the callback.
            return self._execute_async(qname, method, resolved_args,
                                       resolved_kwargs, return_ids,
                                       num_returns, task_id, task_hex,
                                       cgroup=cgroup)
        ctx = getattr(self, "_env_ctx", None)
        if task_id is not None:
            with self._cancel_lock:
                self._running_sync[task_id] = threading.get_ident()
        try:
            # Env covers the whole body, including a streaming method's
            # lazy generator execution.
            with (ctx.applied() if ctx is not None
                  else contextlib.nullcontext()), \
                    _tracing().task_span(qname, trace_ctx,
                                         {"task_id": task_hex or ""}):
                result = method(*resolved_args, **resolved_kwargs)
                if _inspect.iscoroutine(result):
                    import asyncio

                    result = asyncio.run(result)
                if num_returns == "streaming":
                    self.runtime._stream_results(result, task_id, qname)
        finally:
            if task_id is not None:
                with self._cancel_lock:
                    self._running_sync.pop(task_id, None)
                    # Withdraw a cancel that arrived too late, so it
                    # cannot hit the next item on this thread.
                    _clear_async_exc(threading.get_ident())
        if num_returns != "streaming":
            self.runtime._store_results(result, return_ids, num_returns)

    def _ensure_loop(self):
        with self._cancel_lock:
            return self._ensure_loop_locked()

    def _ensure_loop_locked(self):
        if self._loop is not None:
            return
        import asyncio

        self._loop = asyncio.new_event_loop()
        # Async actors default to high concurrency when the user left
        # max_concurrency at 1 (parity: ray's async actors default to
        # 1000 concurrent coroutines).
        limit = int(self.options.max_concurrency)
        if limit <= 1:
            limit = 1000
        self._async_sem = asyncio.Semaphore(limit)
        # Named groups bound their coroutines independently (parity:
        # per-group event loops in the reference; one shared loop with
        # per-group semaphores gives the same isolation contract).
        self._async_group_sems = {
            g: asyncio.Semaphore(max(1, int(n)))
            for g, n in (self.options.concurrency_groups or {}).items()
        }
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, daemon=True,
            name=f"actor-{self.actor_id.hex()[:8]}-loop",
        )
        self._loop_thread.start()

    def _execute_async(self, qname, method, args, kwargs, return_ids,
                       num_returns, task_id, task_hex, cgroup=None):
        import asyncio
        import concurrent.futures as _cf

        self._ensure_loop()
        sem = (self._async_group_sems.get(cgroup, self._async_sem)
               if cgroup else self._async_sem)

        async def body():
            async with sem:
                return await method(*args, **kwargs)

        fut = asyncio.run_coroutine_threadsafe(body(), self._loop)
        if task_id is not None:
            with self._cancel_lock:
                self._inflight_async[task_id] = (fut, return_ids)
        ev = self.runtime.events

        def done(f):
            if task_id is not None:
                with self._cancel_lock:
                    self._inflight_async.pop(task_id, None)
            try:
                result = f.result()
            except BaseException as e:
                if isinstance(e, (asyncio.CancelledError, _cf.CancelledError)):
                    err: BaseException = TaskCancelledError(task_hex or "")
                elif isinstance(e, TaskCancelledError):
                    err = e
                else:
                    err = self._item_error(qname, e)
                for oid in return_ids:
                    self.runtime.store.put_error_if_pending(oid, err)
                if task_hex:
                    ev.record(task_hex, _ev.FAILED, error_message=repr(err))
                return
            try:
                self.runtime._store_results(result, return_ids, num_returns)
                if task_hex:
                    ev.record(task_hex, _ev.FINISHED)
            except BaseException as e:
                err = self._item_error(qname, e)
                for oid in return_ids:
                    self.runtime.store.put_error_if_pending(oid, err)
                if task_hex:
                    ev.record(task_hex, _ev.FAILED, error_message=repr(err))

        fut.add_done_callback(done)
        return _ASYNC_DEFERRED

    def cancel_task(self, task_id: TaskID, force: bool = False) -> None:
        """Cancel one submitted actor task: drop it if queued, cancel
        the coroutine if in-flight async, async-raise into the thread
        if running sync (parity: CancelActorTask semantics — force has
        no stronger meaning for actor tasks)."""
        with self._cancel_lock:
            entry = self._inflight_async.get(task_id)
            tid = self._running_sync.get(task_id)
            if entry is None and tid is None:
                self._cancelled.add(task_id)
                return
            if entry is None:
                # Deliver UNDER the lock: _execute_item's finally
                # unregisters + withdraws pending exceptions under the
                # same lock, so this can never poison a later item on
                # the thread.
                _async_raise(tid, TaskCancelledError)
                return
        # Future.cancel outside the lock: a not-yet-started coroutine
        # cancels synchronously, invoking done() which takes the lock.
        entry[0].cancel()

    def _item_error(self, qname: str, e: BaseException) -> BaseException:
        return TaskError(qname, e)

    def _after_item_error(self, e: BaseException) -> bool:
        """True → stop serving (the loop returns)."""
        if not isinstance(e, Exception):
            # actor dies on SystemExit et al
            self.dead = True
            self.death_reason = repr(e)
            self._post_kill()
            return True
        return False

    def _post_kill(self) -> None:
        """Wake every serve pool (default + named groups) for exit."""
        self.queue.put(None)
        for q in self._group_queues.values():
            q.put(None)

    def _drain(self, err: BaseException):
        # Close the submit gate FIRST: anything enqueued before this
        # point is swept below; anything after seals directly.
        with self._submit_gate:
            self._drained = True
        # In-flight async calls: seal the death error (so consumers
        # can't hang on a stopped loop) and cancel the coroutines.
        with self._cancel_lock:
            inflight = list(self._inflight_async.values())
            self._inflight_async.clear()
        for fut, oids in inflight:
            for oid in oids:
                self.runtime.store.put_error_if_pending(oid, err)
            fut.cancel()
        if self._loop is not None:
            self._loop.call_soon_threadsafe(lambda: None)  # wake the loop
        for q in [self.queue, *self._group_queues.values()]:
            while True:
                try:
                    item = q.get_nowait()
                except _queue.Empty:
                    break
                if item is None:
                    continue
                for oid in item[3]:
                    self.runtime.store.put_error(oid, err)
                if item[4] == "streaming" and len(item) > 5 and item[5]:
                    # Queued-but-never-started stream: index 0 unsealed.
                    self.runtime.store.put_error(
                        ObjectID.for_task_return(item[5], 0), err
                    )
                if len(item) > 5 and item[5]:
                    self.runtime.events.record(item[5].hex(), _ev.FAILED,
                                               error_message=repr(err))

    def _seal_item_error(self, err: BaseException, return_ids, num_returns,
                         task_id) -> None:
        for oid in return_ids:
            self.runtime.store.put_error(oid, err)
        if num_returns == "streaming" and task_id is not None:
            self.runtime.store.put_error(
                ObjectID.for_task_return(task_id, 0), err
            )
        if task_id is not None:
            self.runtime.events.record(task_id.hex(), _ev.FAILED,
                                       error_message=repr(err))

    def _seal_item_dead(self, return_ids, num_returns, task_id) -> None:
        self._seal_item_error(
            ActorDiedError(repr(self.cls), self.death_reason or "dead"),
            return_ids, num_returns, task_id)

    def submit(self, method_name: str, args, kwargs, return_ids, num_returns,
               task_id: Optional[TaskID] = None, trace_ctx=None,
               concurrency_group: Optional[str] = None):
        if self.dead:
            self._seal_item_dead(return_ids, num_returns, task_id)
            return
        if concurrency_group and concurrency_group not in self._group_queues:
            self._seal_item_error(
                TaskError(
                    f"{self.cls.__name__}.{method_name}",
                    ValueError(f"unknown concurrency group "
                               f"{concurrency_group!r}; declared: "
                               f"{sorted(self._group_queues)}")),
                return_ids, num_returns, task_id)
            return
        queue = (self._group_queues[concurrency_group]
                 if concurrency_group else self.queue)
        item = (method_name, args, kwargs, return_ids, num_returns,
                task_id, trace_ctx, concurrency_group)
        if self.options.execute_out_of_order:
            # A call whose ObjectRef args are not sealed yet must not
            # block later calls (parity: OutOfOrderActorSubmitQueue —
            # dependency-ready tasks dispatch immediately).
            deps = [oid for oid in _collect_arg_oids(args, kwargs)
                    if not self.runtime.store.contains(oid)]
            if deps:
                self._ooo_add(queue, item, deps)
                return
        with self._submit_gate:
            if self._drained:
                self._seal_item_dead(return_ids, num_returns, task_id)
                return
            queue.put(item)

    def _ooo_add(self, queue: _queue.Queue, item, deps) -> None:
        """Park a dep-blocked out-of-order call on the shell's single
        dispatcher thread (bounded: O(1) threads regardless of how many
        calls are blocked, unlike a thread per call)."""
        with self._submit_gate:
            if self.dead:
                self._seal_item_dead(item[3], item[4], item[5])
                return
            self._ooo_pending.append((queue, item, deps))
            if self._ooo_thread is None:
                self._ooo_thread = threading.Thread(
                    target=self._ooo_loop, daemon=True,
                    name=f"actor-{self.actor_id.hex()[:8]}-ooo",
                )
                self._ooo_thread.start()

    def _ooo_loop(self) -> None:
        store = self.runtime.store
        while True:
            with self._submit_gate:
                if self.dead:
                    pending, self._ooo_pending = self._ooo_pending, []
                    self._ooo_thread = None
                    break
                if not self._ooo_pending:
                    self._ooo_thread = None
                    return
                snapshot = list(self._ooo_pending)
            ready = [(q, it, deps) for q, it, deps in snapshot
                     if all(store.contains(d) for d in deps)]
            with self._submit_gate:
                for entry in ready:
                    if entry in self._ooo_pending:
                        self._ooo_pending.remove(entry)
                        if not self._drained:
                            entry[0].put(entry[1])
                        else:
                            it = entry[1]
                            self._seal_item_dead(it[3], it[4], it[5])
                remaining = [d for _, _, deps in self._ooo_pending
                             for d in deps if not store.contains(d)]
            if remaining:
                # Woken by ANY dep sealing; bounded timeout re-checks
                # death so a killed actor can't strand the loop.
                store.wait(remaining, 1, 0.5)
        for _, it, _ in pending:
            self._seal_item_dead(it[3], it[4], it[5])

    def kill(self, no_restart: bool = True):
        self.dead = True
        self.no_restart = no_restart
        self.death_reason = "killed via ray_tpu.kill"
        self._post_kill()


class _RemoteInstance:
    """Truthy sentinel: the actor's real instance lives in a worker
    process; drivers only know it was constructed."""

    def __repr__(self):
        return "<instance in worker process>"


_REMOTE_INSTANCE = _RemoteInstance()


class _ProcessActorShell(_ActorShell):
    """Actor hosted in a dedicated OS worker process (parity: each actor
    is its own worker process, gcs_actor_scheduler.cc LeaseWorkerFromNode
    → the actor owns that worker for life).  The driver side keeps the
    same queue/ordering/restart machinery as the in-process shell; only
    construction and method execution cross the process boundary.

    Crash semantics the thread shell cannot give: kill -9 of the worker
    → in-flight calls fail with ActorDiedError and the restart FSM
    re-leases a fresh process; ray_tpu.kill() preemptively terminates
    the process, interrupting even a stuck method."""

    def _construct(self):
        import cloudpickle as _cp

        pool = self.runtime._pool_for(self.allocation)
        wh = pool.lease(dedicated=True)
        try:
            # Init args ship raw — ObjectRefs stay refs, matching the
            # thread shell (the instance resolves them itself if/when
            # it wants the values).
            rep = wh.call(
                "actor_create",
                spec=_cp.dumps((self.cls, self.init_args,
                                self.init_kwargs)),
                env=self.options.runtime_env,
                env_plugins=self.runtime._ship_env(
                    self.options.runtime_env),
                max_concurrency=self.options.max_concurrency,
                concurrency_groups=dict(
                    self.options.concurrency_groups or {}),
            )
            if isinstance(rep, dict):
                self.runtime.apply_ref_batches(
                    rep, self.runtime._worker_ref_key(wh))
        except BaseException:
            # A half-constructed worker may hold broken state — never
            # return it to the pool.
            wh.terminate(graceful=False)
            raise
        self._worker = wh
        wh.on_death = self._worker_died
        self._env_ctx = None  # env is applied worker-side
        self.instance = _REMOTE_INSTANCE

    def _worker_died(self):
        if self.dead:
            return
        self.dead = True
        self.death_reason = "worker process died"
        self._post_kill()

    def _worker_label(self) -> str:
        return f"pid-{getattr(self._worker, 'pid', '?')}"

    def _execute_item(self, qname, method_name, args, kwargs, return_ids,
                      num_returns, task_id, trace_ctx, task_hex,
                      cgroup=None):
        import cloudpickle as _cp

        method = getattr(self.cls, method_name, None)
        if (_inspect.iscoroutinefunction(method)
                and num_returns != "streaming"):
            # Async actor method: dispatch WITHOUT blocking the serve
            # loop, so N calls are in flight to the worker together and
            # interleave on its shared event loop (parity: fiber.h
            # async actors — the thread shell's _execute_async
            # equivalent across the process boundary).
            return self._execute_async_remote(
                qname, method_name, args, kwargs, return_ids,
                num_returns, task_id, trace_ctx, task_hex, cgroup=cgroup)
        wire_args, wire_kwargs = self.runtime._wire_args(args, kwargs)
        if task_id is not None:
            with self._cancel_lock:
                self._running_sync[task_id] = True  # in-flight marker
        try:
            with _tracing().task_span(qname, trace_ctx,
                                      {"task_id": task_hex or ""}):
                rep = self._worker.call(
                    "actor_task", method=method_name,
                    spec=_cp.dumps((wire_args, wire_kwargs)),
                    num_returns=num_returns,
                    returns=[oid.binary() for oid in return_ids],
                    task=(task_id.binary() if task_id is not None else b""),
                    trace_ctx=_tracing().capture_context(),
                    cgroup=cgroup,
                )
        finally:
            if task_id is not None:
                with self._cancel_lock:
                    self._running_sync.pop(task_id, None)
        wkey = self.runtime._worker_ref_key(self._worker)
        if num_returns != "streaming":
            self.runtime.seal_remote_results(
                return_ids, rep, wkey,
                node_hex=getattr(self._worker, "node_hex", None))
        else:
            self.runtime.apply_ref_batches(rep, wkey)

    def _execute_async_remote(self, qname, method_name, args, kwargs,
                              return_ids, num_returns, task_id, trace_ctx,
                              task_hex, cgroup=None):
        import cloudpickle as _cp

        from ray_tpu.core.exceptions import WorkerDiedError

        with self._cancel_lock:
            if self._async_sem is None:
                limit = int(self.options.max_concurrency)
                self._async_sem = threading.Semaphore(
                    limit if limit > 1 else 1000)
                self._async_group_sems = {
                    g: threading.Semaphore(max(1, int(n)))
                    for g, n in
                    (self.options.concurrency_groups or {}).items()
                }
        wire_args, wire_kwargs = self.runtime._wire_args(args, kwargs)
        spec = _cp.dumps((wire_args, wire_kwargs))
        wh = self._worker
        # At the concurrency cap the serve loop blocks here — the same
        # bound the thread shell's asyncio.Semaphore enforces (named
        # groups bound independently).
        sem = (self._async_group_sems.get(cgroup, self._async_sem)
               if cgroup else self._async_sem)
        sem.acquire()
        if task_id is not None:
            with self._cancel_lock:
                self._running_sync[task_id] = True
        ev = self.runtime.events
        ctx = _tracing().capture_context()

        def run():
            try:
                try:
                    rep = wh.call(
                        "actor_task", method=method_name, spec=spec,
                        num_returns=num_returns,
                        returns=[oid.binary() for oid in return_ids],
                        task=(task_id.binary() if task_id is not None
                              else b""),
                        trace_ctx=ctx,
                        cgroup=cgroup,
                    )
                finally:
                    if task_id is not None:
                        with self._cancel_lock:
                            self._running_sync.pop(task_id, None)
                self.runtime.seal_remote_results(
                    return_ids, rep,
                    self.runtime._worker_ref_key(wh),
                    node_hex=getattr(wh, "node_hex", None))
                if task_hex:
                    ev.record(task_hex, _ev.FINISHED)
            except BaseException as e:
                if isinstance(e, WorkerDiedError):
                    err: BaseException = ActorDiedError(
                        repr(self.cls), "worker process died")
                    self._worker_died()
                elif isinstance(e, TaskCancelledError):
                    err = e
                else:
                    err = TaskError(qname, e)
                for oid in return_ids:
                    self.runtime.store.put_error_if_pending(oid, err)
                if task_hex:
                    ev.record(task_hex, _ev.FAILED, error_message=repr(err))
            finally:
                sem.release()

        threading.Thread(target=run, daemon=True,
                         name=f"{qname}-async").start()
        return _ASYNC_DEFERRED

    def _item_error(self, qname: str, e: BaseException) -> BaseException:
        from ray_tpu.core.exceptions import WorkerDiedError

        if isinstance(e, WorkerDiedError):
            return ActorDiedError(repr(self.cls), "worker process died")
        return TaskError(qname, e)

    def _after_item_error(self, e: BaseException) -> bool:
        from ray_tpu.core.exceptions import WorkerDiedError

        if isinstance(e, WorkerDiedError):
            self._worker_died()
            return False  # drain remaining items fast via dead calls
        # SystemExit et al raised worker-side and transported here —
        # mirror the thread shell.
        return super()._after_item_error(e)

    def _drain(self, err: BaseException):
        wh = getattr(self, "_worker", None)
        if wh is not None:
            wh.on_death = None
            wh.terminate(graceful=not wh.dead)
            self._worker = None
        super()._drain(err)

    def cancel_task(self, task_id: TaskID, force: bool = False) -> None:
        with self._cancel_lock:
            running = task_id in self._running_sync
            if not running:
                self._cancelled.add(task_id)
                return
        wh = getattr(self, "_worker", None)
        if wh is not None:
            try:
                wh.call("cancel", task=task_id.binary())
            except Exception:
                pass  # worker gone — death semantics already apply

    def kill(self, no_restart: bool = True):
        super().kill(no_restart)
        # Preemptive: a stuck or long-running method dies with the
        # process (the thread shell can only ask nicely).
        wh = getattr(self, "_worker", None)
        if wh is not None:
            wh.terminate(graceful=False)


@dataclasses.dataclass
class _PGState:
    pg: PlacementGroup
    bundles: List[Bundle]
    ready_oid: ObjectID
    lifetime: Optional[str] = None
    removed: bool = False


class LocalRuntime:
    def __init__(self, *, resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 job_id: Optional[JobID] = None):
        cfg = get_config()
        total = dict(resources or {})
        if "CPU" not in total:
            total["CPU"] = float(cfg.num_workers_soft_limit or 8)
        total.setdefault("memory", 64 * 1024**3)
        self.store = LocalObjectStore()
        # Cluster KV (parity: GcsKvManager — function table, job info,
        # runtime envs and usage stats live here).
        from ray_tpu.core.kv import KvStore

        self.kv = KvStore()
        # GCS-side task-event ring (parity: GcsTaskManager, see events.py).
        self.events = _ev.TaskEventBuffer(
            max_tasks=getattr(cfg, "task_events_max_num", 16384)
        )
        self.job_id = job_id or JobID.next()
        self.driver_task_id = TaskID.for_driver(self.job_id)
        self._put_counter = itertools.count(1)
        self._lock = threading.Lock()
        # Ready queue (deps satisfied, awaiting resources) + the
        # dependency-wakeup index: missing oid → tasks parked on it
        # (parity: DependencyManager, raylet/dependency_manager.h:51 —
        # tasks wake when their deps become local, no polling).  Deque:
        # the dispatcher pops the head O(1) — a list's pop(0) would be
        # O(n) per dispatch with 100k tasks queued.
        self._pending: "_collections.deque[_PendingTask]" = \
            _collections.deque()
        self._waiting_deps: Dict[ObjectID, List[_PendingTask]] = {}
        self._dispatch_cv = threading.Condition()
        # Pooled executor threads for thread-mode task bodies.
        self._exec_pool = _CachedThreadPool()
        # Feasibility memo for (demand, string-strategy) pairs —
        # submit-path hot cache, cleared on any topology change.  The
        # epoch guards against caching a verdict computed against
        # pre-change topology (compute is not under the topology lock).
        self._feasible_cache: Dict[Any, bool] = {}
        self._topology_epoch = 0
        self._shutdown = False
        self._actors: Dict[ActorID, _ActorShell] = {}
        self._named_actors: Dict[str, ActorID] = {}
        self._nodes: Dict[NodeID, NodeState] = {}
        self._node_order: List[NodeID] = []  # stable order for hybrid packing
        # Native C++ scheduler core (parity: the raylet's C++
        # ClusterResourceScheduler); None → pure-Python ledgers.
        try:
            from ray_tpu.core.native_scheduler import NativeClusterScheduler

            self._native_sched = NativeClusterScheduler(
                spread_threshold=cfg.scheduler_spread_threshold
            )
        except Exception:
            self._native_sched = None
        self._node_int_ids = itertools.count(1)
        self._nodes_by_int: Dict[int, NodeState] = {}
        self._pgs: Dict[PlacementGroupID, _PGState] = {}
        self._named_pgs: Dict[str, PlacementGroupID] = {}
        # Tombstones for the actor state table, bounded (parity: GCS keeps
        # DEAD actors queryable up to
        # RAY_maximum_gcs_destroyed_actor_cached_count).
        self._dead_actors: Any = _collections.deque(maxlen=1024)
        # Lineage for object reconstruction (parity: TaskManager keeps
        # specs of finished tasks while their outputs are referenced,
        # reference_count lineage pinning; bounded like
        # RAY_max_lineage_bytes).  Keyed by return ObjectID → task spec.
        self._lineage: "_collections.OrderedDict[ObjectID, _PendingTask]" = \
            _collections.OrderedDict()
        self._lineage_cap = 10000
        # Where each task output's primary copy lives (parity: the
        # object directory's location view).
        self._object_locations: Dict[ObjectID, NodeID] = {}
        # Reconstruction bookkeeping: in-flight task specs (by identity)
        # and attempts per spec, bounded by max_retries (parity: the
        # reference counts reconstruction against the retry budget).
        self._reconstructing: set = set()
        self._recon_attempts: Dict[int, int] = {}
        # Daemon-dispatched (external) tasks in flight: task_bin →
        # {"pt", "node_hex", "acquired"} (see register_external_task).
        self._external: Dict[bytes, Dict[str, Any]] = {}
        # Completion casts with no matching register: same-epoch
        # reordering CANNOT happen (local_task/done/failed ride the
        # node channel's serial FIFO lane — wire.py serial_ops, which
        # is load-bearing, do not remove it); what lands here is
        # stale-epoch garbage after a head restart, absorbed bounded
        # and consumed by a register only in pathological replays.
        self._external_early: Dict[bytes, Dict[str, Any]] = {}
        # Running normal tasks, for cancellation: task_id → {"pt", and
        # "thread" (thread mode) or "worker" (process mode)} (parity:
        # the executing-tasks map HandleCancelTask consults).
        self._running_tasks: Dict[TaskID, Dict[str, Any]] = {}
        # Serializes all bundle (re-)reservation: concurrent node events
        # must not double-place the same pending bundle.
        self._pg_reserve_lock = threading.Lock()
        # Readers hitting a lost object trigger lazy lineage
        # reconstruction (parity: recovery on fetch failure).
        self.store.lost_object_callback = self._reconstruct_object
        # Ownership / reference counting (parity: ReferenceCounter,
        # reference_count.h:61): local handles via ObjectRef hooks,
        # seal pins for in-flight task returns, borrows from worker
        # processes, nested pins from sealed values.  Zero → the free
        # thread releases the store copy and this object's lineage
        # entry (which in turn drops the task spec's argument handles —
        # lineage bounded by the ref count).
        from ray_tpu.core import object_ref as _object_ref
        from ray_tpu.core.refcount import ReferenceCounter

        self.refs = ReferenceCounter(self._on_refs_zero)
        # RLock: release_stream (reachable from generator __del__ via
        # the defer path, and directly in tests) takes it while seal
        # callbacks may be on the same stack.
        self._seal_pin_lock = threading.RLock()
        self._seal_pinned: set = set()
        # Streams whose consumer generator was dropped: items the
        # producer seals afterwards are released on arrival instead of
        # leaking (bounded tombstone ring).
        from ray_tpu.core.refcount import TombstoneSet

        self._dropped_streams = TombstoneSet(4096)
        self.store.on_sealed = self._on_object_sealed
        self.store.on_nested = self.refs.add_nested
        # Cross-node object plane: pull remote primary copies through
        # the owning daemon's channel; free them when refs hit zero.
        self.store.fetch_remote = self._fetch_remote_bytes
        self.store.release_remote = self._release_remote
        self._ref_hooks = (self.refs.add_local, self.refs.remove_local)
        _object_ref.install_ref_hooks(*self._ref_hooks)
        # Execution backend: thread (in-process) or pooled OS worker
        # processes over the shared-memory object plane (parity: the
        # raylet's WorkerPool of forked language workers,
        # raylet/worker_pool.h:156).  RAYTPU_WORKERS=process.
        self.worker_mode = cfg.workers
        self.worker_pool = None
        # Cluster log plane (parity: per-node log files + log_monitor.py
        # tailing them + dashboard log views): this node's workers write
        # to log_dir; the monitor ships complete lines to the LogBuffer;
        # remote daemons ship theirs over the head channel.
        from ray_tpu.core.pubsub import Publisher
        from ray_tpu.util.log_monitor import LogBuffer

        # General pubsub channels (parity: GCS pubsub, publisher.h:307
        # — node/actor/logs/error channels, long-poll subscribers).
        self.pubsub = Publisher()
        self.logs = LogBuffer(cfg.log_buffer_lines)
        self.log_dir = None
        self._log_monitor = None
        if self.worker_mode == "process":
            from ray_tpu.core.worker_pool import WorkerPool
            from ray_tpu.util.log_monitor import (
                LogMonitor,
                resolve_log_dir,
            )

            self.log_dir = resolve_log_dir()
            self.worker_pool = WorkerPool(self)
            self._log_monitor = LogMonitor(
                self.log_dir, self._publish_local_logs,
                cfg.log_monitor_period_s)
        # Control-plane persistence (parity: Redis-backed GCS storage —
        # KV + detached-actor specs + detached PG specs survive a
        # driver restart, gcs/store_client/redis_store_client.h:33).
        self._detached_specs: Dict[str, bytes] = {}
        # Restored detached-actor specs that could not place yet (no
        # capacity at restart — e.g. the head came back before its
        # daemons rejoined).  add_node retries them (parity: pending
        # GCS actor-table entries placed on node add).
        self._pending_restores: Dict[str, bytes] = {}
        self._rejoin_lock = threading.Lock()
        self._persist = None
        self._restored_tables = None
        if cfg.gcs_persist_path:
            from ray_tpu.core.gcs_persistence import GcsPersistence

            self._persist = GcsPersistence(
                cfg.gcs_persist_path, cfg.gcs_flush_period_s,
                mirror_paths=[p.strip() for p in
                              cfg.gcs_persist_mirrors.split(",")
                              if p.strip()],
            )
            self._restored_tables = self._persist.load()
            if self._restored_tables:
                self.kv.restore(self._restored_tables.get("kv") or {})
            self.kv.on_mutate = self._persist.mark_dirty
        self.head_node_id = self.add_node(total, labels)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="dispatcher", daemon=True
        )
        self._dispatcher.start()
        # Detached actors re-create AFTER the dispatcher is live (their
        # constructors may submit work).  Parity: GCS restart replays
        # the actor table and reschedules detached actors
        # (gcs_init_data.cc + GcsActorManager::Initialize).
        if self._restored_tables:
            self._restore_detached(self._restored_tables)
        self._restored_tables = None  # only needed during init
        if self._persist is not None:
            self._persist.start_flusher(self._gcs_tables)

    # -- cluster membership ------------------------------------------------

    def add_node(self, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 node_id: Optional[NodeID] = None) -> NodeID:
        if node_id is None:
            node_id = NodeID.from_random()
        int_id = next(self._node_int_ids)
        native = ((self._native_sched, int_id)
                  if self._native_sched is not None else None)
        node = NodeState(node_id, dict(resources), labels,
                         native=native, int_id=int_id)
        with self._lock:
            self._nodes[node_id] = node
            self._node_order.append(node_id)
            self._nodes_by_int[int_id] = node
            pending_pgs = [st for st in self._pgs.values()
                           if not st.removed
                           and any(b.node_id is None for b in st.bundles)]
        self._topology_epoch += 1
        self._feasible_cache.clear()  # new capacity changes feasibility
        # Register with the native scheduler LAST: the node must not be
        # natively pickable before the Python tables can map it back.
        if self._native_sched is not None:
            self._native_sched.add_node(int_id, dict(resources))
        # New capacity may satisfy pending placement groups
        # (parity: GcsPlacementGroupManager::OnNodeAdd retry).
        for st in pending_pgs:
            self._reserve_bundles(
                st, [b for b in st.bundles if b.node_id is None]
            )
        if getattr(self, "_pending_restores", None):
            threading.Thread(target=self._retry_detached_restores,
                             daemon=True, name="detached-restore").start()
        self.pubsub.publish("node", {
            "event": "added", "node_id": node_id.hex(),
            "resources": dict(resources),
        })
        self._notify()
        return node_id

    def register_remote_node(self, agent, resources: Dict[str, float],
                             labels: Optional[Dict[str, str]],
                             addr: Tuple[str, int]) -> NodeID:
        """Register a node daemon that joined over TCP (parity: raylet
        registration with the GCS, gcs_node_manager.cc RegisterNode).
        The daemon owns its local worker pool + shm arena; the head
        schedules onto it like any node and dispatches over ``agent``."""
        node_id = self.add_node(resources, labels)
        with self._lock:
            node = self._nodes[node_id]
            node.agent = agent
            node.addr = tuple(addr)
        agent.bind(self, node)
        return node_id

    def rejoin_remote_node(self, agent, node_id_bin: bytes,
                           resources: Dict[str, float],
                           labels: Optional[Dict[str, str]],
                           addr: Tuple[str, int],
                           objects: List[Tuple[bytes, int]]):
        """A daemon that was already a cluster member reconnects —
        either this head restarted (its node table is empty) or the
        daemon's channel blipped.  Returns ``(node_id, accepted)``:
        ``accepted=False`` tells the daemon its previous identity is
        stale (the head declared it dead and rescheduled its work) and
        it must re-register fresh.  On acceptance the daemon keeps its
        node id and its advertised objects are re-pinned as locations
        (parity: raylets re-registering with a Redis-recovered GCS,
        gcs/gcs_server/gcs_server.cc:517-518 + gcs_node_manager
        re-registration; object locations re-reported by the owner)."""
        want = NodeID(node_id_bin)
        # One rejoin admitted per node id: a daemon that redialed while
        # its first attempt was still registering must not double-insert
        # the id into the node tables (add_node takes _lock repeatedly,
        # so the exists-check alone is not atomic with the insert).
        with self._rejoin_lock:
            with self._lock:
                existing = self._nodes.get(want)
            if existing is not None:
                # The head never restarted: it has already declared this
                # node dead (channel close → kill_node) and recovered
                # its actors/objects elsewhere — or a concurrent rejoin
                # already won.  Resurrecting the id would race that.
                return want, False
            node_id = self.add_node(resources, labels, node_id=want)
        with self._lock:
            node = self._nodes[node_id]
            node.agent = agent
            node.addr = tuple(addr)
        agent.bind(self, node)
        # Re-pin the daemon's surviving objects: location table + store
        # remote-seal marks + a borrow keyed under the node so the pins
        # evaporate if the node later dies.
        node_hex = node_id.hex()
        restore_key = node_hex[:12] + "/restored"
        for oid_bin, size in objects:
            oid = ObjectID(oid_bin)
            if self.store.is_freed(oid):
                continue
            self.seal_remote_at(oid, node_hex, size)
            self.refs.add_borrow(restore_key, oid)
        return node_id, True

    def seal_remote_at(self, oid: ObjectID, node_hex: str,
                       size: int) -> None:
        """Record a seal whose bytes live in a remote daemon's arena:
        store marks the location; the location table feeds node-death
        recovery (parity: object directory location update)."""
        self.store.mark_remote_sealed(oid, node_hex, size)
        with self._lock:
            node = next((n for n in self._nodes.values()
                         if n.node_id.hex() == node_hex), None)
            if node is not None:
                self._object_locations[oid] = node.node_id

    def node_by_hex(self, node_hex: str) -> Optional[NodeState]:
        with self._lock:
            for n in self._nodes.values():
                if n.node_id.hex() == node_hex:
                    return n
        return None

    def kill_node(self, node_id: NodeID) -> None:
        """Mark a node dead; its actors die (restartable ones restart
        elsewhere), its PG bundles are re-reserved on surviving nodes
        (parity: GcsNodeManager death → actor fate + bundle reschedule)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return
            node.alive = False
            self._topology_epoch += 1
            self._feasible_cache.clear()
            if self._native_sched is not None:
                self._native_sched.kill_node(node.int_id)
            doomed = [self._actors[a] for a in list(node.actor_ids)
                      if a in self._actors]
        if node.agent is not None:
            # Borrows held by the dead node's workers evaporate (their
            # keys are namespaced under the node id), and the channel
            # closes (idempotent if the close is what killed the node).
            self.refs.drop_worker_prefix(node_id.hex()[:12] + "/")
            node.agent.close()
        for shell in doomed:
            shell.death_reason = "node died"
            shell.dead = True
            shell._post_kill()
        # Re-reserve PG bundles that lived on this node.
        with self._lock:
            pgs = list(self._pgs.values())
        for st in pgs:
            lost = [b for b in st.bundles
                    if b.node_id == node_id and not st.removed]
            for b in lost:
                with b.lock:
                    b.node_id = None
                    b.available = {}
            if lost:
                self._reserve_bundles(st, lost)
        self._recover_lost_objects(node_id)
        self._reroute_external_on_node_death(node_id.hex())
        self.pubsub.publish("node", {"event": "died",
                                     "node_id": node_id.hex()})
        self._notify()

    def _recover_lost_objects(self, node_id: NodeID) -> None:
        """Objects whose primary copy lived on the dead node are
        invalidated.  Retriable outputs stay in the "lost" state until a
        reader fetches them, which triggers lazy lineage reconstruction
        (parity: ObjectRecoveryManager recovers on fetch, not on node
        death — no eager replay of side effects for outputs nobody
        reads).  Non-retriable outputs are sealed with ObjectLostError.
        ray.put objects have no lineage and live on the driver node, so
        they are never in the location map (parity: put objects are not
        reconstructable)."""
        from ray_tpu.core.exceptions import ObjectLostError

        with self._lock:
            lost = [oid for oid, nid in self._object_locations.items()
                    if nid == node_id]
            for oid in lost:
                del self._object_locations[oid]
            unrecoverable = [
                oid for oid in lost
                if (pt := self._lineage.get(oid)) is None
                or pt.options.max_retries == 0
            ]
        for oid in lost:
            invalidated = self.store.invalidate(oid)
            if invalidated and oid in unrecoverable:
                self.store.put_error(oid, ObjectLostError(oid.hex()))
        # Tasks parked on a just-lost dep would otherwise wait for a
        # reconstruction nobody triggers (recovery is fetch-lazy, and a
        # parked task never fetches) — kick it for them now.
        with self._dispatch_cv:
            parked_lost = [oid for oid in lost if oid in self._waiting_deps]
        for oid in parked_lost:
            self._reconstruct_object(oid)

    def _reconstruct_object(self, oid: ObjectID) -> None:
        """Resubmit the creating task of a lost object (parity:
        ObjectRecoveryManager::ReconstructObject via
        TaskManager::ResubmitTask).  Idempotent while a rebuild is in
        flight; attempts are bounded by the task's max_retries."""
        from ray_tpu.core.exceptions import ObjectLostError

        with self._lock:
            pt = self._lineage.get(oid)
            if pt is None:
                pt_missing = True
            elif pt.task_id.binary() in self._external:
                # Still running on its daemon: the node-death reroute
                # owns re-enqueue; a fetch-triggered rebuild here would
                # double-run it.
                return
            else:
                pt_missing = False
                key = id(pt)
                if key in self._reconstructing:
                    return
                attempts = self._recon_attempts.get(key, 0)
                if attempts >= max(1, pt.options.max_retries):
                    exhausted = True
                else:
                    exhausted = False
                    self._hydrate_external(pt)  # no-op for normal tasks
                    self._recon_attempts[key] = attempts + 1
                    self._reconstructing.add(key)
                    options = pt.options
                    strategy = options.effective_strategy()
                    if isinstance(strategy, NodeAffinitySchedulingStrategy):
                        want = (strategy.node_id.hex()
                                if isinstance(strategy.node_id, NodeID)
                                else str(strategy.node_id))
                        alive = any(n.alive and n.node_id.hex() == want
                                    for n in self._nodes.values())
                        if not alive:
                            # Pinned node is gone; rebuild anywhere.
                            options = dataclasses.replace(
                                options, scheduling_strategy="DEFAULT"
                            )
                    fresh = dataclasses.replace(
                        pt, options=options,
                        retries_left=options.max_retries,
                        on_done=lambda k=key: self._reconstructing.discard(k),
                    )
        if pt_missing:
            self.store.put_error_if_pending(oid, ObjectLostError(oid.hex()))
            return
        if exhausted:
            for roid in pt.return_ids:
                self.store.put_error_if_pending(
                    roid, ObjectLostError(roid.hex())
                )
            return
        self._enqueue_task(fresh)

    def _alive_nodes(self) -> List[NodeState]:
        return [self._nodes[i] for i in self._node_order
                if self._nodes[i].alive]

    # -- cross-node object plane -------------------------------------------

    def _fetch_remote_bytes(self, node_hex: str, oid: ObjectID,
                            size: int) -> bytes:
        """Pull one object's framed bytes from the node daemon that
        holds its primary copy (parity: PullManager → remote object
        manager chunk transfer)."""
        node = self.node_by_hex(node_hex)
        if node is None or not node.alive or node.agent is None:
            raise OSError(f"object {oid.hex()}: node {node_hex} is gone")
        return node.agent.pull(oid, size)

    def _release_remote(self, node_hex: Optional[str],
                        oid: ObjectID) -> None:
        """Free node-side copies of a released object.  Broadcast to
        every joined daemon: replicas pulled by consumer nodes are not
        location-tracked at the head (parity trade-off vs the
        reference's per-copy object directory), and the cast is a
        fire-and-forget socket write — cheap at this scale."""
        with self._lock:
            agents = [n.agent for n in self._nodes.values()
                      if n.agent is not None and n.alive]
        for agent in agents:
            agent.free([oid.binary()])

    # -- control-plane persistence -----------------------------------------

    def _gcs_tables(self) -> Dict[str, Any]:
        """Durable control-plane snapshot (parity: the GCS tables Redis
        holds: KV, actor specs for detached actors, PG specs)."""
        with self._lock:
            detached = dict(self._detached_specs)
            pgs = [
                {"bundles": [dict(b.resources) for b in st.bundles],
                 "strategy": st.pg.strategy, "name": st.pg.name}
                for st in self._pgs.values()
                if st.lifetime == "detached" and st.pg.name
                and not st.removed
            ]
        return {"kv": self.kv.dump(), "detached_actors": detached,
                "detached_pgs": pgs}

    def _mark_gcs_dirty(self) -> None:
        if self._persist is not None:
            self._persist.mark_dirty()

    def _restore_detached(self, tables: Dict[str, Any]) -> None:
        """Re-create persisted detached actors/PGs.  Actor memory state
        is NOT recovered — same contract as the reference restarting a
        detached actor after its process died (checkpoint in the actor
        if its state matters)."""
        import cloudpickle as _cp

        for spec in tables.get("detached_pgs") or ():
            try:
                self.create_placement_group(
                    spec["bundles"], spec["strategy"], spec["name"],
                    "detached",
                )
            except Exception:
                pass  # e.g. name re-taken; best-effort replay
        for name, blob in (tables.get("detached_actors") or {}).items():
            try:
                cls, args, kwargs, options = _cp.loads(blob)
                # Bounded wait: a cluster that shrank since the snapshot
                # must skip unplaceable actors, not hang init forever.
                self.create_actor(cls, args, kwargs, options,
                                  alloc_timeout=5.0)
            except Exception:
                # Unplaceable/unreplayable NOW ≠ gone: keep the spec in
                # the durable table so a later restart with capacity can
                # still recover it (parity: an unplaceable detached
                # actor stays pending in the GCS actor table), and queue
                # it for retry when capacity joins (daemons rejoin a
                # restarted head AFTER its init).
                with self._lock:
                    self._detached_specs.setdefault(name, blob)
                    self._pending_restores.setdefault(name, blob)

    def _retry_detached_restores(self) -> None:
        """Retry restored-but-unplaced detached actors after a node
        joined.  Every queued spec gets one attempt per round — a spec
        that still cannot place must not strand later specs that can."""
        import cloudpickle as _cp

        with self._lock:
            pending = dict(self._pending_restores)
            self._pending_restores.clear()
        failed: Dict[str, bytes] = {}
        for name, blob in pending.items():
            try:
                cls, args, kwargs, options = _cp.loads(blob)
            except Exception:
                continue  # unreplayable spec; durable table keeps it
            with self._lock:
                taken = bool(options.name
                             and options.name in self._named_actors)
            if taken:
                continue  # someone already (re)created it
            try:
                self.create_actor(cls, args, kwargs, options,
                                  alloc_timeout=5.0)
            except Exception:
                # Still unplaceable (or lost a create race): back in
                # the queue; the next node join retries.
                failed[name] = blob
        if failed:
            with self._lock:
                for name, blob in failed.items():
                    self._pending_restores.setdefault(name, blob)

    # -- objects -----------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        oid = self.alloc_put_oid()
        self.store.put_value(oid, value)
        return ObjectRef(oid)

    def alloc_put_oid(self) -> ObjectID:
        """Fresh put-object id (also used for worker-side puts that
        write the bytes directly into the shared arena)."""
        return ObjectID.from_put(self.driver_task_id,
                                 next(self._put_counter))

    # -- ownership / GC ----------------------------------------------------

    def _record_lineage_locked(self, return_ids: Sequence[ObjectID],
                               pt: _PendingTask) -> None:
        """Insert into the lineage table with cap eviction.  Evicting
        lineage also drops the location entry and reconstruction
        counters — the three tables stay bounded together.  Caller
        holds _lock."""
        for oid in return_ids:
            self._lineage[oid] = pt
        while len(self._lineage) > self._lineage_cap:
            old_oid, old_pt = self._lineage.popitem(last=False)
            self._object_locations.pop(old_oid, None)
            self._recon_attempts.pop(id(old_pt), None)

    def _pin_returns(self, return_ids: Sequence[ObjectID]) -> None:
        """Pin task-return oids from submission until seal, so dropping
        the future before the task finishes can't free the slot under
        the executor (parity: submitted-task return refs)."""
        with self._seal_pin_lock:
            for oid in return_ids:
                self.refs.add_seal_pin(oid)
                self._seal_pinned.add(oid)

    def _on_object_sealed(self, oid: ObjectID) -> None:
        with self._seal_pin_lock:
            pinned = oid in self._seal_pinned
            if pinned:
                self._seal_pinned.discard(oid)
            dropped_stream = (self._dropped_streams
                              and oid.task_id() in self._dropped_streams)
        if pinned:
            self.refs.remove_seal_pin(oid)
        if dropped_stream:
            # Item sealed into an abandoned stream — nobody can ever
            # consume it (the generator is gone); release on arrival.
            self.store.release(oid)
        # Dependency wakeup (parity: DependencyManager::HandleObjectLocal
        # moving tasks to ready) — tasks parked on this oid whose last
        # missing dep just sealed go to the ready queue.
        if self._waiting_deps:
            with self._dispatch_cv:
                waiters = self._waiting_deps.pop(oid, None)
                if waiters:
                    woke = False
                    for pt in waiters:
                        if pt.waiting_on is not None:
                            pt.waiting_on.discard(oid)
                        if not pt.waiting_on:
                            pt.waiting_on = None
                            self._pending.append(pt)
                            woke = True
                    if woke:
                        self._dispatch_cv.notify_all()

    def _on_refs_zero(self, oid: ObjectID) -> None:
        """Free thread: last reference to ``oid`` dropped.  Release the
        store copy and this object's lineage/location entries; dropping
        the lineage task spec releases its argument handles, cascading
        the collection upstream (parity: lineage_ref_count_)."""
        with self._lock:
            self._lineage.pop(oid, None)
            self._object_locations.pop(oid, None)
        self.store.release(oid, tombstone=True)

    def release_stream_async(self, task_id: TaskID, from_index: int) -> None:
        """GC-safe entry for generator __del__: defers the release to
        the free thread (release_stream takes store/runtime locks that
        may already be held by the thread a GC pause interrupted)."""
        self.refs.defer(lambda: self.release_stream(task_id, from_index))

    def release_stream(self, task_id: TaskID, from_index: int) -> None:
        """A dropped ObjectRefGenerator releases sealed-but-unconsumed
        stream items (consumed items have their own counted handles).
        The stream is also marked dropped FIRST, so items a still-running
        producer seals after this scan are released on arrival
        (_on_object_sealed) instead of leaking."""
        with self._seal_pin_lock:
            self._dropped_streams.add(task_id)
        i = from_index
        while True:
            oid = ObjectID.for_task_return(task_id, i)
            if not self.store.contains(oid):
                return
            self.store.release(oid)
            i += 1

    def _wire_args(self, args: tuple, kwargs: dict):
        """Replace top-level ObjectRef args with their WIRE
        representation for shipping to a worker process — shared-arena
        pointers for large objects, framed bytes otherwise.  Never
        deserializes here (the worker does the one decode); sealed
        errors re-raise, matching resolve_args semantics."""
        from ray_tpu.core.wire import WireRef

        def enc(v):
            if not isinstance(v, ObjectRef):
                return v
            kind, payload = self.store.get_wire_loc(v.id)
            if kind == "err":
                raise payload
            if kind == "at":
                # Remote primary copy: ship the location marker; the
                # executing worker fetches through its node daemon
                # (local-arena hit when the task landed on the owning
                # node — the common consumer-follows-producer case).
                return WireRef("fetch", payload[1], v.id.binary())
            return WireRef(kind, payload, v.id.binary())

        return (tuple(enc(a) for a in args),
                {k: enc(v) for k, v in kwargs.items()})

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        out = [self.store.get(r.id, timeout) for r in ref_list]
        return out[0] if single else out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True):
        ids = [r.id for r in refs]
        ready_ids, pending_ids = self.store.wait(ids, num_returns, timeout)
        by_id = {r.id: r for r in refs}
        return [by_id[i] for i in ready_ids], [by_id[i] for i in pending_ids]

    def resolve_args(self, args: tuple, kwargs: dict) -> Tuple[tuple, dict]:
        """Replace top-level ObjectRef args with their values
        (parity: LocalDependencyResolver inlining).  Wire-form specs
        (nested submissions) carry WireRef("fetch") markers instead of
        handles — resolve those too so a re-enqueued external task can
        execute in-process."""
        from ray_tpu.core.wire import WireRef

        def res(v):
            if isinstance(v, ObjectRef):
                return self.get(v)
            if isinstance(v, WireRef) and v.kind == "fetch":
                return self.get(ObjectRef(ObjectID(v.oid)))
            return v

        return tuple(res(a) for a in args), {k: res(v) for k, v in kwargs.items()}

    def _task_arg_oids(self, pt: _PendingTask) -> List[ObjectID]:
        if pt.arg_oids is not None:
            return pt.arg_oids
        return [v.id for v in list(pt.args) + list(pt.kwargs.values())
                if isinstance(v, ObjectRef)]

    def _enqueue_task(self, pt: _PendingTask) -> None:
        """Queue for dispatch: straight to the ready queue when every
        ObjectRef arg is local, else parked in the dependency index to
        be woken by the seal callback (parity: DependencyManager
        subscribe → wake, no polling).  The registration and the seal
        callback's resolution both run under _dispatch_cv, so a seal
        racing this enqueue either makes contains() true here or finds
        the parked entry there — never neither."""
        with self._dispatch_cv:
            missing = []
            for oid in self._task_arg_oids(pt):
                if not self.store.contains(oid):
                    missing.append(oid)
                    if self.store._state(oid).lost:
                        # Parked fetcher triggers recovery (parity: the
                        # dependency resolver's recovery path).
                        self._reconstruct_object(oid)
            if missing:
                self._park_locked(pt, missing)
                return
            pt.waiting_on = None
            self._pending.append(pt)
            self._dispatch_cv.notify_all()

    def _park_locked(self, pt: _PendingTask,
                     missing: List[ObjectID]) -> None:
        """Park in the dependency index; caller holds _dispatch_cv.
        After registering, re-check each dep: the seal callback's
        UNLOCKED emptiness fast-path may have skipped a wakeup while we
        were parking — the locked contains() re-check closes that race."""
        pt.waiting_on = set(missing)
        for oid in pt.waiting_on:
            self._waiting_deps.setdefault(oid, []).append(pt)
        for oid in list(pt.waiting_on):
            if self.store.contains(oid):
                pt.waiting_on.discard(oid)
                lst = self._waiting_deps.get(oid)
                if lst is not None:
                    try:
                        lst.remove(pt)
                    except ValueError:
                        pass
                    if not lst:
                        del self._waiting_deps[oid]
        if not pt.waiting_on:
            pt.waiting_on = None
            self._pending.append(pt)
            self._dispatch_cv.notify_all()

    def _deps_still_ready_locked(self, pt: _PendingTask) -> bool:
        """Cheap pre-dispatch re-check: a dep sealed at enqueue time may
        have been invalidated since (node death).  Re-parks the task and
        kicks reconstruction if so.  Caller holds _dispatch_cv."""
        missing = []
        for oid in self._task_arg_oids(pt):
            if not self.store.contains(oid):
                missing.append(oid)
                if self.store._state(oid).lost:
                    self._reconstruct_object(oid)
        if not missing:
            return True
        self._park_locked(pt, missing)
        return False

    def _store_results(self, result: Any, return_ids: List[ObjectID],
                       num_returns: int):
        if num_returns == 1:
            self.store.put_value(return_ids[0], result)
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(values)} values"
                )
            for oid, v in zip(return_ids, values):
                self.store.put_value(oid, v)

    def _stream_results(self, result: Any, task_id: TaskID,
                        function_name: str) -> None:
        """Seal each yielded item at its return index as it is produced,
        then the end-of-stream sentinel (parity: the streaming-generator
        executor in _raylet.pyx:918).  Mid-stream errors are sealed at
        the failing index and re-raised."""
        from ray_tpu.core.generator import EndOfStream

        i = 0
        try:
            if not hasattr(result, "__iter__"):
                raise TypeError(
                    f"streaming task {function_name!r} must return an "
                    f"iterable/generator, got {type(result).__name__}"
                )
            for item in result:
                self.store.put_value(
                    ObjectID.for_task_return(task_id, i), item
                )
                i += 1
        except BaseException as e:
            # Seal the error at the failing index so the consumer's
            # next() unblocks with an error ref instead of hanging.
            self.store.put_error(
                ObjectID.for_task_return(task_id, i),
                e if isinstance(e, TaskError) else TaskError(
                    function_name, e
                ),
            )
            raise
        self.store.put_error(
            ObjectID.for_task_return(task_id, i), EndOfStream()
        )

    def _seal_stream_failure(self, task_id: TaskID,
                             err: BaseException) -> None:
        """Seal ``err`` at the first UNSEALED stream index.  A worker
        process that dies mid-stream leaves a sealed prefix [0, k);
        sealing only index 0 would leave a consumer already past it
        blocked forever on index k."""
        i = 0
        while True:
            oid = ObjectID.for_task_return(task_id, i)
            if self.store.is_freed(oid):
                # Consumed-and-dropped index (refcount freed it): not
                # the first unsealed — keep scanning, or the consumer
                # hangs at the real one.
                i += 1
                continue
            if self.store.put_error_if_pending(oid, err):
                return
            if self.store.peek_error(oid) is not None:
                # Already ended (error or EndOfStream sentinel) — the
                # consumer can't hang; don't clobber.
                return
            i += 1

    # -- scheduling --------------------------------------------------------

    def _feasible(self, demand: Dict[str, float], strategy: Any) -> bool:
        """Memoized _cluster_can_fit for hashable (string) strategies;
        the cache clears whenever cluster topology changes."""
        if not isinstance(strategy, str):
            return self._cluster_can_fit(demand, strategy)
        key = (tuple(sorted(demand.items())), strategy)
        cached = self._feasible_cache.get(key)
        if cached is not None:
            return cached
        epoch = self._topology_epoch
        ok = self._cluster_can_fit(demand, strategy)
        if epoch == self._topology_epoch and len(self._feasible_cache) < 1024:
            self._feasible_cache[key] = ok
        return ok

    def _cluster_can_fit(self, demand: Dict[str, float],
                         strategy: Any = "DEFAULT") -> bool:
        """Strategy-aware feasibility: a hard affinity/label constraint
        that no live node can ever satisfy must fail at submission, not
        hang (parity: Ray's unschedulable-task error)."""
        nodes = self._alive_nodes()
        if (isinstance(strategy, NodeAffinitySchedulingStrategy)
                and not strategy.soft):
            want = (strategy.node_id.hex()
                    if isinstance(strategy.node_id, NodeID)
                    else str(strategy.node_id))
            nodes = [n for n in nodes if n.node_id.hex() == want]
        elif isinstance(strategy, NodeLabelSchedulingStrategy):
            nodes = [n for n in nodes if n.matches_labels(strategy.hard)]
        return any(n.pool.can_fit(demand) for n in nodes)

    def _try_allocate(self, demand: Dict[str, float],
                      strategy: Any) -> Optional[_Allocation]:
        """Cluster phase of the two-phase scheduler: pick a node (or PG
        bundle) and acquire resources.  Returns None when nothing fits
        right now (parity: ClusterTaskManager::QueueAndScheduleTask +
        HybridSchedulingPolicy)."""
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            st = self._pgs.get(strategy.placement_group.id)
            if st is None or st.removed:
                raise ValueError("placement group removed or unknown")
            idx = strategy.placement_group_bundle_index
            if idx >= len(st.bundles):
                raise ValueError(
                    f"bundle index {idx} out of range for a "
                    f"{len(st.bundles)}-bundle placement group"
                )
            candidates = (st.bundles if idx < 0 else [st.bundles[idx]])
            if not any(all(b.resources.get(k, 0) >= v
                           for k, v in demand.items())
                       for b in candidates):
                raise ValueError(
                    f"demand {demand} exceeds every candidate bundle's "
                    f"reservation — infeasible"
                )
            for b in candidates:
                if b.node_id is not None and b.try_acquire(demand):
                    node = self._nodes.get(b.node_id)
                    return _Allocation(node, b, demand)
            return None

        nodes = self._alive_nodes()
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            want = (strategy.node_id.hex()
                    if isinstance(strategy.node_id, NodeID)
                    else str(strategy.node_id))
            exact = [n for n in nodes if n.node_id.hex() == want]
            if exact and exact[0].pool.try_acquire(demand):
                return _Allocation(exact[0], None, demand)
            if not strategy.soft:
                return None
            nodes = [n for n in nodes if n.node_id.hex() != want] or nodes
            strategy = "DEFAULT"

        if isinstance(strategy, NodeLabelSchedulingStrategy):
            hard = [n for n in nodes if n.matches_labels(strategy.hard)]
            soft = [n for n in hard if n.matches_labels(strategy.soft)]
            for n in soft + [n for n in hard if n not in soft]:
                if n.pool.try_acquire(demand):
                    return _Allocation(n, None, demand)
            return None

        if self._native_sched is not None and strategy in ("SPREAD",
                                                           "DEFAULT"):
            # Atomic pick+acquire in the C++ core (one lock, no Python
            # loop races; parity: ClusterResourceScheduler picking under
            # the raylet's single-threaded executor).
            from ray_tpu.core import native_scheduler as _ns

            with self._lock:
                all_alive = len(nodes) == sum(
                    1 for nd in self._nodes.values() if nd.alive
                )
            cands = None if all_alive else [n.int_id for n in nodes]
            chosen = self._native_sched.pick_and_acquire(
                demand,
                _ns.SPREAD if strategy == "SPREAD" else _ns.HYBRID,
                candidates=cands,
            )
            if chosen is None:
                return None
            node = self._nodes_by_int.get(chosen)
            if node is None:  # can't happen post-registration ordering
                self._native_sched.release(chosen, demand)
                return None
            return _Allocation(node, None, demand)

        if strategy == "SPREAD":
            for n in sorted(nodes, key=lambda n: n.pool.utilization()):
                if n.pool.try_acquire(demand):
                    return _Allocation(n, None, demand)
            return None

        # DEFAULT hybrid: pack onto the first (stable-order) node below the
        # utilization threshold, else fall back to least-utilized
        # (parity: policy/hybrid_scheduling_policy.h:28-46, threshold 0.5).
        threshold = 0.5
        for n in nodes:
            if n.pool.utilization() < threshold and n.pool.try_acquire(demand):
                return _Allocation(n, None, demand)
        for n in sorted(nodes, key=lambda n: n.pool.utilization()):
            if n.pool.try_acquire(demand):
                return _Allocation(n, None, demand)
        return None

    # -- tasks -------------------------------------------------------------

    def _ship_env(self, renv):
        """Worker-bound runtime-env payload: plugins named by the env
        ship by value so the worker can materialize them (parity: the
        reference distributes plugin setup through the per-node
        runtime-env agent).  The pickled blob is memoized per
        (plugin set, registry version) — NOT re-pickled per dispatch."""
        if not renv:
            return None
        try:
            names = frozenset(renv.keys())
        except AttributeError:
            return None
        from ray_tpu import runtime_env as _re

        used = frozenset(k for k in _re._plugins if k in names)
        if not used:
            return None
        key = (used, _re._plugins_version)
        cache = getattr(self, "_env_plugin_cache", None)
        if cache is None or cache[0] != key:
            import cloudpickle

            cache = (key, cloudpickle.dumps(
                {k: _re._plugins[k] for k in used}))
            self._env_plugin_cache = cache
        return cache[1]

    def submit_task(self, fn: Callable, args: tuple, kwargs: dict,
                    options: TaskOptions,
                    trace_ctx: Optional[Dict[str, str]] = None,
                    arg_oids: Optional[List[ObjectID]] = None,
                    pin_oids: Optional[List[ObjectID]] = None,
                    ) -> List[ObjectRef]:
        demand = options.resource_demand()
        strategy = options.effective_strategy()
        if (not isinstance(strategy, PlacementGroupSchedulingStrategy)
                and not self._feasible(demand, strategy)):
            raise ValueError(
                f"task {getattr(fn, '__name__', fn)!r} demands {demand} "
                f"under {strategy!r}, which no node can ever satisfy — "
                f"infeasible"
            )
        task_id = TaskID.of(ActorID.nil_for_job(self.job_id))
        streaming = options.num_returns == "streaming"
        return_ids = [] if streaming else [
            ObjectID.for_task_return(task_id, i)
            for i in range(options.num_returns)
        ]
        self._pin_returns(return_ids)
        pt = _PendingTask(
            fn=fn, args=args, kwargs=kwargs, options=options,
            return_ids=return_ids,
            # Streaming tasks never retry: the consumer may already have
            # observed a prefix of the stream (see generator.py).
            retries_left=0 if streaming else options.max_retries,
            task_id=task_id, function_name=getattr(fn, "__name__", repr(fn)),
            streaming=streaming,
            trace_ctx=(trace_ctx if trace_ctx is not None
                       else _tracing().capture_context()),
        )
        pt.demand = demand  # computed once; dispatch + events reuse it
        if arg_oids is not None:
            # Nested submission with wire-form args: pin the explicit
            # deps AND the pin-only inner refs with head-side handles
            # (the normal path pins both via the ObjectRef instances
            # living inside pt.args).  Only arg_oids park the task.
            pt.arg_oids = arg_oids
            pt.arg_refs = [ObjectRef(o)
                           for o in arg_oids + list(pin_oids or ())]
        self.events.record(
            task_id.hex(), _ev.PENDING_NODE_ASSIGNMENT,
            name=pt.function_name, type=_ev.NORMAL_TASK,
            job_id=self.job_id.hex(), required_resources=demand,
        )
        if not streaming:
            with self._lock:
                self._record_lineage_locked(return_ids, pt)
        self._enqueue_task(pt)
        if streaming:
            from ray_tpu.core.generator import ObjectRefGenerator

            return ObjectRefGenerator(task_id)
        return [ObjectRef(oid) for oid in return_ids]

    def _dispatch_loop(self):
        """Event-driven dispatcher: sleeps until woken by a new ready
        task, a dependency seal, or a resource release (parity: the
        raylet scheduling on events, not a poll — the 1 s timeout is
        only a lost-wakeup safety net; round 1 polled every 20 ms)."""
        while True:
            with self._dispatch_cv:
                while not self._shutdown:
                    runnable = self._next_runnable_locked()
                    if runnable is not None:
                        break
                    self._dispatch_cv.wait(1.0)
                if self._shutdown:
                    return
            self._start_task(*runnable)

    def _next_runnable_locked(self):
        """Pop the first dispatchable ready task.  Head-pop is O(1) on
        the hot path (homogeneous tasks: the head either fits or
        nothing does); skipped tasks are restored in order."""
        skipped: List[_PendingTask] = []
        runnable = None
        try:
            while self._pending:
                pt = self._pending.popleft()
                if pt.cancelled:
                    continue  # cancel() already sealed its outputs
                # Dep liveness re-check: sealed-at-enqueue deps may have
                # been invalidated by a node death since.
                if not self._deps_still_ready_locked(pt):
                    continue  # re-parked (or re-appended, if it resolved)
                try:
                    alloc = self._try_allocate(
                        pt.demand if pt.demand is not None
                        else pt.options.resource_demand(),
                        pt.options.effective_strategy(),
                    )
                except ValueError as e:
                    err = TaskError(pt.function_name, e)
                    for oid in pt.return_ids:
                        self.store.put_error(oid, err)
                    if pt.streaming:
                        self.store.put_error(
                            ObjectID.for_task_return(pt.task_id, 0), err
                        )
                    self.events.record(
                        pt.task_id.hex(), _ev.FAILED, name=pt.function_name,
                        attempt=pt.options.max_retries - pt.retries_left,
                        error_message=str(e),
                    )
                    # Keep scanning: with no poll, returning here would
                    # stall runnable tasks behind a poisoned head for a
                    # full safety-net wait.
                    continue
                if alloc is not None:
                    runnable = (pt, alloc)
                    return runnable
                skipped.append(pt)
            return None
        finally:
            # Restore skipped tasks at the front, original order first.
            self._pending.extendleft(reversed(skipped))

    def _start_task(self, pt: _PendingTask, alloc: _Allocation):
        # Streaming tasks force retries_left=0, so derive their attempt
        # as 0 rather than max_retries - 0.
        attempt = (0 if pt.streaming
                   else pt.options.max_retries - pt.retries_left)

        def run():
            requeued = False
            if pt.cancelled:
                # Cancelled between scheduling and start: never run.
                self._seal_cancelled(pt.task_id, pt.return_ids,
                                     pt.streaming)
                if pt.on_done is not None:
                    pt.on_done()
                alloc.release()
                self._notify()
                return
            with self._lock:
                self._running_tasks[pt.task_id] = {
                    "pt": pt, "thread": threading.get_ident(),
                }
            self.events.record(
                pt.task_id.hex(), _ev.RUNNING, name=pt.function_name,
                attempt=attempt, job_id=self.job_id.hex(),
                node_id=(alloc.node.node_id.hex() if alloc.node else None),
                worker=threading.current_thread().name,
                required_resources=(pt.demand if pt.demand is not None
                                    else pt.options.resource_demand()),
            )
            try:
                pool = self._pool_for(alloc)
                if pool is not None:
                    with _tracing().task_span(
                        pt.function_name, pt.trace_ctx,
                        {"task_id": pt.task_id.hex(), "attempt": attempt},
                    ):
                        self._execute_task_remote(pt, pool)
                else:
                    args, kwargs = self.resolve_args(pt.args, pt.kwargs)
                    if pt.options.runtime_env:
                        from ray_tpu.runtime_env import materialize

                        env_cm = materialize(
                            pt.options.runtime_env).applied()
                    else:
                        env_cm = contextlib.nullcontext()
                    # The env must cover the whole body — for a
                    # streaming task the generator body runs inside
                    # _stream_results.
                    with env_cm, _tracing().task_span(
                        pt.function_name, pt.trace_ctx,
                        {"task_id": pt.task_id.hex(), "attempt": attempt},
                    ):
                        result = pt.fn(*args, **kwargs)
                        if pt.streaming:
                            self._stream_results(result, pt.task_id,
                                                 pt.function_name)
                    if not pt.streaming:
                        self._store_results(result, pt.return_ids,
                                            pt.options.num_returns)
                if not pt.streaming:
                    if alloc.node is not None:
                        with self._lock:
                            for oid in pt.return_ids:
                                self._object_locations[oid] = \
                                    alloc.node.node_id
                self.events.record(pt.task_id.hex(), _ev.FINISHED,
                                   attempt=attempt)
            except Exception as e:
                self.events.record(pt.task_id.hex(), _ev.FAILED,
                                   attempt=attempt, error_message=repr(e))
                cancelled = pt.cancelled or isinstance(e, TaskCancelledError)
                if cancelled:
                    # Cancelled tasks seal TaskCancelledError and NEVER
                    # retry (parity: cancellation beats max_retries).
                    self._seal_cancelled(
                        pt.task_id, pt.return_ids, pt.streaming,
                        err=e if isinstance(e, TaskCancelledError) else None,
                    )
                elif pt.streaming:
                    # Failures before/inside the stream must unblock the
                    # consumer at the first unsealed index (a worker
                    # process may have died after producing a prefix;
                    # in-process failures already sealed the failing
                    # index, making this a no-op there).
                    self._seal_stream_failure(
                        pt.task_id,
                        e if isinstance(e, TaskError)
                        else TaskError(pt.function_name, e),
                    )
                if not cancelled and pt.retries_left > 0:
                    pt.retries_left -= 1
                    requeued = True
                    self._enqueue_task(pt)
                elif not cancelled and not pt.streaming:
                    err = e if isinstance(e, TaskError) else TaskError(
                        pt.function_name, e
                    )
                    for oid in pt.return_ids:
                        self.store.put_error(oid, err)
                    # Retries exhausted: surface cluster-wide (parity:
                    # the GCS error-info channel).
                    self.pubsub.publish("error", {
                        "source": pt.function_name,
                        "task_id": pt.task_id.hex(),
                        "message": repr(e)[:500],
                    })
            finally:
                with self._lock:
                    self._running_tasks.pop(pt.task_id, None)
                    # Withdraw a too-late cancel UNDER the lock (cancel
                    # delivers under it too), so it can't hit an
                    # unrelated future task on this thread.
                    _clear_async_exc(threading.get_ident())
                # on_done (the reconstruction in-flight guard) must NOT
                # fire when the task was re-queued for retry — the work
                # is still in flight.
                if pt.on_done is not None and not requeued:
                    pt.on_done()
                alloc.release()
                self._notify()

        self._exec_pool.submit(run)

    def _pool_for(self, alloc: _Allocation):
        """Execution backend for an allocation: the remote node's daemon
        agent when the task landed on a joined node, else the head's
        local worker pool (None → thread-mode in-process execution)."""
        if alloc.node is not None and alloc.node.agent is not None:
            return alloc.node.agent
        return self.worker_pool

    def _execute_task_remote(self, pt: _PendingTask, pool=None) -> None:
        """Run one task on a leased worker process (parity: OnWorkerIdle
        pushing onto a leased worker, direct_task_transport.cc:191 →
        HandlePushTask, core_worker.cc:3072).  ``pool`` is the head's
        WorkerPool or a remote node's agent (same lease/release
        surface).  Raises the worker-side exception (or WorkerDiedError
        on a crash) so the caller's retry path treats remote failures
        exactly like local ones."""
        import cloudpickle

        if pool is None:
            pool = self.worker_pool
        wire_args, wire_kwargs = self._wire_args(pt.args, pt.kwargs)
        spec = cloudpickle.dumps((wire_args, wire_kwargs))
        fhash, fblob = self._export_fn(pt.fn)
        wh = pool.lease()
        with self._lock:
            entry = self._running_tasks.get(pt.task_id)
            if entry is not None:
                entry["worker"] = wh  # cancellation targets the process
        try:
            # Function ship-once (parity: the function manager exporting
            # a remote function to each worker once, keyed by hash —
            # python/ray/_private/function_manager.py): the pickled fn
            # rides only the worker's FIRST call; later calls send the
            # hash + args, which is most of the per-task pickle cost.
            shipped = getattr(wh, "shipped_fns", None)
            if shipped is None:
                shipped = wh.shipped_fns = set()
            rep = wh.call(
                "task", spec=spec, name=pt.function_name,
                fn_hash=fhash,
                fn_blob=(None if fhash in shipped else fblob),
                streaming=pt.streaming, task=pt.task_id.binary(),
                num_returns=pt.options.num_returns,
                returns=[oid.binary() for oid in pt.return_ids],
                env=pt.options.runtime_env,
                env_plugins=self._ship_env(pt.options.runtime_env),
                # Capture INSIDE the driver-side task span so nested
                # submissions from the worker parent to this task.
                trace_ctx=_tracing().capture_context(),
            )
            shipped.add(fhash)
        finally:
            pool.release(wh)
        wkey = self._worker_ref_key(wh)
        if pt.streaming:
            # The worker sealed every index + the sentinel.
            self.apply_ref_batches(rep, wkey)
            return
        self.seal_remote_results(pt.return_ids, rep, wkey,
                                 node_hex=getattr(wh, "node_hex", None))

    def _export_fn(self, fn) -> Tuple[str, bytes]:
        """(hash, pickled blob) of a task function, pickled once per fn
        object (parity: function-manager export; closure mutations
        after decoration do not re-export, as in the reference)."""
        cache = getattr(self, "_fn_blob_cache", None)
        if cache is None:
            import weakref

            cache = self._fn_blob_cache = weakref.WeakKeyDictionary()
            self._fn_blob_lock = threading.Lock()
        try:
            with self._fn_blob_lock:
                hit = cache.get(fn)
            if hit is not None:
                return hit
        except TypeError:
            hit = None  # unhashable/unweakrefable callable
        import hashlib

        import cloudpickle

        blob = cloudpickle.dumps(fn)
        fhash = hashlib.sha1(blob).hexdigest()[:16]
        try:
            with self._fn_blob_lock:
                cache[fn] = (fhash, blob)
        except TypeError:
            pass
        return fhash, blob

    @staticmethod
    def _worker_ref_key(wh) -> str:
        rk = getattr(wh, "ref_key", None)
        if rk is not None:
            return rk
        from ray_tpu.core.worker_pool import _wkey

        return _wkey(wh.chan)

    def apply_ref_batches(self, rep: Dict[str, Any], worker_key: str,
                          which: str = "both") -> None:
        """Apply borrow add/del batches piggybacked on a worker reply."""
        # Worker-finished spans and metric snapshots also ride the
        # reply (pop: this runs twice per reply on the sealing path —
        # add then rem).
        if isinstance(rep, dict):
            spans = rep.pop("spans", None)
            if spans and _tracing().is_enabled():
                _tracing().ingest(spans)
            snap = rep.pop("metrics", None)
            if snap:
                from ray_tpu.util import metrics as _metrics

                _metrics.merge_remote(worker_key, snap)
            reqev_rows = rep.pop("request_events", None)
            if reqev_rows:
                from ray_tpu.serve import request_events as _request_events

                _request_events.merge_remote(worker_key, reqev_rows)
            frec_events = rep.pop("flightrec", None)
            if frec_events:
                from ray_tpu.util import flight_recorder as _frec

                _frec.ingest(worker_key, frec_events)
            ts_points = rep.pop("timeseries", None)
            if ts_points:
                from ray_tpu.util import timeseries as _timeseries

                _timeseries.ingest(worker_key, ts_points)
        if which in ("both", "add"):
            for b in rep.get("ref_add") or ():
                self.refs.add_borrow(worker_key, ObjectID(b))
        if which in ("both", "rem"):
            for b in rep.get("ref_rem") or ():
                self.refs.remove_borrow(worker_key, ObjectID(b))

    def seal_remote_results(self, return_ids: Sequence[ObjectID],
                            rep: Dict[str, Any],
                            worker_key: Optional[str] = None,
                            node_hex: Optional[str] = None) -> None:
        """Seal a worker task reply's results.  Order matters: borrow
        ADDS first (they may cover refs inside the returned values),
        then nested pins, then the seal, then borrow DELS — so a del of
        a ref riding in the reply can never free it before its pin.
        ``node_hex`` set → the executing worker lives on a remote node
        daemon; "shm" entries stayed in THAT node's arena and seal as
        remote locations."""
        if worker_key is not None:
            self.apply_ref_batches(rep, worker_key, which="add")
        nested = rep.get("nested") or [()] * len(return_ids)
        for oid, (kind, payload), inner in zip(return_ids,
                                               rep["results"], nested):
            if inner:
                self.refs.add_nested(oid, [ObjectID(b) for b in inner])
            if kind == "shm":
                if node_hex:
                    self.seal_remote_at(oid, node_hex, payload)
                else:
                    self.store.mark_shm_sealed(oid, payload)
            else:
                self.store.put_serialized(oid, payload)
        if worker_key is not None:
            self.apply_ref_batches(rep, worker_key, which="rem")

    # -- daemon-dispatched (external) tasks --------------------------------
    #
    # Parity: raylet-local scheduling over the Ray Syncer's resource
    # view — a daemon dispatches its workers' nested submissions onto
    # its own pool and the head only does the owner-side bookkeeping,
    # off the submit critical path (see core/local_dispatch.py).

    def register_external_task(self, task_bin: bytes,
                               return_bins: List[bytes], spec: bytes,
                               options: TaskOptions,
                               deps: List[bytes],
                               demand: Dict[str, float],
                               submit_wkey: str, node_hex: str,
                               pins: Optional[List[bytes]] = None,
                               ) -> None:
        """Owner-side bookkeeping for a task a daemon dispatched
        locally: return-oid pins + submitter borrows, explicit-dep
        pins, lineage (lazily hydratable from ``spec``), events, and
        the cached-ledger debit.  Applied from the daemon's ordered
        cast, so it lands before any later ref-drop or get that could
        mention these ids."""
        task_id = TaskID(task_bin)
        return_ids = [ObjectID(b) for b in return_bins]
        self._pin_returns(return_ids)
        pt = _PendingTask(
            fn=None, args=(), kwargs={}, options=options,
            return_ids=return_ids, retries_left=options.max_retries,
            task_id=task_id,
            function_name=options.name or "nested",
            spec_blob=spec,
            arg_oids=[ObjectID(b) for b in deps],
        )
        pt.arg_refs = [ObjectRef(ObjectID(b))
                       for b in list(deps) + list(pins or ())]
        pt.demand = demand
        node = self.node_by_hex(node_hex)
        if node is None or not node.alive:
            # The daemon died between sending this cast and its
            # processing — the node-death reroute already ran (and
            # found nothing), and no completion cast will ever come.
            # Re-run through the normal scheduler instead of
            # registering an orphan (reconstruction explicitly skips
            # in-flight external tasks).  Safe double-run-wise: the
            # dead daemon's workers are killed on rejoin.
            self._hydrate_external(pt)
            with self._lock:
                self._record_lineage_locked(return_ids, pt)
            for b in return_bins:
                self.refs.add_borrow(submit_wkey, ObjectID(b))
            self._enqueue_task(pt)
            return
        acquired = bool(node.pool.try_acquire(demand))
        with self._lock:
            self._record_lineage_locked(return_ids, pt)
            self._external[task_bin] = {
                "pt": pt, "node_hex": node_hex, "acquired": acquired,
            }
        for b in return_bins:
            self.refs.add_borrow(submit_wkey, ObjectID(b))
        self.events.record(
            task_id.hex(), _ev.PENDING_NODE_ASSIGNMENT,
            name=pt.function_name, type=_ev.NORMAL_TASK,
            job_id=self.job_id.hex(), required_resources=demand,
        )
        self.events.record(task_id.hex(), _ev.RUNNING,
                           node_id=node_hex)
        # Defensive only: the serial lane orders register before its
        # completion within an epoch, so a hit here means a replayed
        # stale completion — applying it beats orphaning the task.
        with self._lock:
            early = self._external_early.pop(task_bin, None)
        if early is not None:
            self.finish_external_task(task_bin, return_bins, **early)

    def _hydrate_external(self, pt: _PendingTask) -> None:
        """Materialize fn/args/kwargs from the cast's spec — only when
        the head itself must re-run the task (retry after a local
        worker crash, reconstruction after node loss).  Args hold
        WireRef("fetch") markers, so a re-dispatch executes on any
        node."""
        if pt.fn is not None or pt.spec_blob is None:
            return
        import cloudpickle

        pt.fn, pt.args, pt.kwargs = cloudpickle.loads(pt.spec_blob)

    def _release_external(self, rec: Dict[str, Any]) -> None:
        if rec.get("acquired"):
            node = self.node_by_hex(rec["node_hex"])
            if node is not None:
                node.pool.release(rec["pt"].demand or {})
            rec["acquired"] = False

    def finish_external_task(self, task_bin: bytes,
                             return_bins: List[bytes],
                             rep: Optional[Dict[str, Any]],
                             exec_wkey: Optional[str],
                             node_hex: str,
                             error: Optional[BaseException] = None,
                             retryable: bool = False) -> None:
        """Completion of a daemon-dispatched task.  Success seals the
        results (shm entries as locations on the executing node);
        an app failure seals the error; an infra failure (local worker
        crash) re-enqueues through the normal scheduler while retries
        remain — the same retry semantics the head path has."""
        with self._lock:
            rec = self._external.pop(task_bin, None)
            if rec is None:
                # Unknown epoch (head restart) — or a register that
                # re-routed at a dead node.  Park bounded; mostly
                # garbage that ages out of the cap.
                self._external_early[task_bin] = {
                    "rep": rep, "exec_wkey": exec_wkey,
                    "node_hex": node_hex, "error": error,
                    "retryable": retryable,
                }
                while len(self._external_early) > 10000:
                    self._external_early.pop(
                        next(iter(self._external_early)))
                return
        pt: _PendingTask = rec["pt"]
        self._release_external(rec)
        task_id = pt.task_id
        if rep is not None:
            self.seal_remote_results(pt.return_ids, rep, exec_wkey,
                                     node_hex=node_hex)
            self.events.record(task_id.hex(), _ev.FINISHED)
            self._notify()
            return
        if retryable and not pt.cancelled and pt.retries_left > 0:
            pt.retries_left -= 1
            self._hydrate_external(pt)
            self.events.record(task_id.hex(), _ev.PENDING_NODE_ASSIGNMENT,
                               name=pt.function_name)
            self._enqueue_task(pt)
            return
        from ray_tpu.core.exceptions import TaskError

        if pt.cancelled:
            self._seal_cancelled(task_id, pt.return_ids, pt.streaming)
            self.events.record(task_id.hex(), _ev.FAILED,
                               error_message="cancelled")
        else:
            err = error if error is not None else TaskError(
                f"task {task_id.hex()[:12]} failed on node "
                f"{node_hex[:12]}")
            for oid in pt.return_ids:
                self.store.put_error(oid, err)
            self.events.record(task_id.hex(), _ev.FAILED,
                               error_message=repr(err))
        self._notify()

    def _reroute_external_on_node_death(self, node_hex: str) -> None:
        """Daemon died with local tasks in flight: re-enqueue each one
        through the normal scheduler (retries permitting) — the cast
        gave the head everything it needs to re-run them elsewhere."""
        with self._lock:
            doomed = [(b, rec) for b, rec in self._external.items()
                      if rec["node_hex"] == node_hex]
        from ray_tpu.core.exceptions import WorkerDiedError

        for task_bin, rec in doomed:
            self.finish_external_task(
                task_bin, [o.binary() for o in rec["pt"].return_ids],
                None, None, node_hex,
                error=WorkerDiedError(f"node {node_hex[:12]} died"),
                retryable=True)

    def resource_view(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        """Seq-free per-node availability snapshot for the view sync
        (parity: the Ray Syncer's NodeResourceInfo broadcast)."""
        out: Dict[str, Dict[str, Dict[str, float]]] = {}
        with self._lock:
            nodes = list(self._nodes.values())
        for n in nodes:
            if not n.alive:
                continue
            out[n.node_id.hex()] = {
                "available": dict(n.pool.available),
                "total": dict(n.pool.total),
            }
        return out

    def _notify(self):
        with self._dispatch_cv:
            self._dispatch_cv.notify_all()

    # -- cancellation ------------------------------------------------------

    def _seal_cancelled(self, task_id: TaskID,
                        return_ids: Sequence[ObjectID], streaming: bool,
                        err: Optional[BaseException] = None
                        ) -> BaseException:
        """Seal TaskCancelledError on a task's outputs — the single
        sealing path for every cancellation site (queued, pre-start,
        failed-running, queued-actor)."""
        err = err or TaskCancelledError(task_id.hex())
        for roid in return_ids:
            self.store.put_error_if_pending(roid, err)
        if streaming:
            self._seal_stream_failure(task_id, err)
        return err

    def cancel(self, oid: ObjectID, force: bool = False) -> None:
        """Cancel the task that produces ``oid`` (parity: ray.cancel —
        core_worker.cc HandleCancelTask + _raylet.pyx:1806).  Pending
        tasks are dropped; running tasks get a cooperative async
        exception (thread mode) or a cancel RPC / process kill
        (process mode, force=True).  A finished task is a no-op."""
        task_id = oid.task_id()
        # 1. Queued (not yet dispatched) normal task — ready queue or
        # parked in the dependency index.
        target = None
        with self._dispatch_cv:
            for pt in self._pending:
                if pt.task_id == task_id:
                    target = pt
                    pt.cancelled = True
                    self._pending.remove(pt)
                    break
            if target is None:
                for lst in self._waiting_deps.values():
                    for pt in lst:
                        if pt.task_id == task_id:
                            target = pt
                            pt.cancelled = True
                            break
                    if target is not None:
                        break
                if target is not None:
                    # Unpark from every dep list it sits in.
                    for dep in list(target.waiting_on or ()):
                        lst = self._waiting_deps.get(dep)
                        if lst is not None:
                            try:
                                lst.remove(target)
                            except ValueError:
                                pass
                            if not lst:
                                del self._waiting_deps[dep]
                    target.waiting_on = None
        if target is not None:
            self._seal_cancelled(task_id, target.return_ids,
                                 target.streaming)
            if target.on_done is not None:
                target.on_done()
            self.events.record(task_id.hex(), _ev.FAILED,
                               error_message="cancelled")
            return
        # 1b. Running on a node daemon's local fast path: mark, then
        # ask THAT daemon (the head never held the worker lease).
        with self._lock:
            rec = self._external.get(task_id.binary())
            if rec is not None:
                rec["pt"].cancelled = True
                node = self._nodes.get(
                    NodeID(bytes.fromhex(rec["node_hex"])))
        if rec is not None:
            if node is not None and node.agent is not None:
                node.agent.chan.cast("cancel_local",
                                     task=task_id.binary(), force=force)
            return
        # 2. Running normal task.
        wh = None
        with self._lock:
            info = self._running_tasks.get(task_id)
            if info is not None:
                info["pt"].cancelled = True
                wh = info.get("worker")
                if wh is None:
                    # Deliver UNDER the lock — run()'s finally withdraws
                    # pending exceptions under the same lock, so a
                    # too-late cancel can't poison the thread's next task.
                    _async_raise(info["thread"], TaskCancelledError)
        if info is not None:
            if wh is not None:
                if force:
                    # Hard kill: the lease-holder sees WorkerDiedError,
                    # which the cancelled flag converts to
                    # TaskCancelledError with no retry.
                    wh.terminate(graceful=False)
                else:
                    try:
                        wh.call("cancel", task=task_id.binary())
                    except Exception:
                        pass  # worker died — death semantics apply
            return
        # 3. Actor task (the task id embeds its actor).
        with self._lock:
            shell = self._actors.get(task_id.actor_id())
        if shell is not None:
            shell.cancel_task(task_id, force)
        # 4. Already finished or unknown: no-op (parity: cancelling a
        # completed task has no effect).

    # -- actors ------------------------------------------------------------

    def create_actor(self, cls: type, args: tuple, kwargs: dict,
                     options: ActorOptions,
                     alloc_timeout: Optional[float] = None):
        if options.name:
            with self._lock:
                existing = self._named_actors.get(options.name)
                shell = self._actors.get(existing) if existing else None
            if shell is not None:
                if options.get_if_exists:
                    return shell, ObjectRef(shell._creation_oid)
                raise ValueError(f"actor name {options.name!r} already taken")
        demand = options.resource_demand()
        strategy = options.effective_strategy()
        if (not isinstance(strategy, PlacementGroupSchedulingStrategy)
                and not self._cluster_can_fit(demand, strategy)):
            raise ValueError(
                f"actor {cls.__name__!r} demands {demand} under "
                f"{strategy!r}, which no node can ever satisfy — infeasible"
            )
        # Actors hold their resources for their lifetime; block until
        # capacity frees up (woken by _notify on every release).
        # alloc_timeout bounds the wait (used by detached-actor replay,
        # where a shrunken cluster must not hang init forever).
        deadline = (None if alloc_timeout is None
                    else time.monotonic() + alloc_timeout)
        while True:
            alloc = self._try_allocate(demand, strategy)
            if alloc is not None:
                break
            if deadline is not None and time.monotonic() >= deadline:
                raise ValueError(
                    f"actor {cls.__name__!r}: no capacity for {demand} "
                    f"within {alloc_timeout}s"
                )
            with self._dispatch_cv:
                self._dispatch_cv.wait(0.05)
        actor_id = ActorID.of(self.job_id)
        creation_task_id = TaskID.of(actor_id)
        creation_oid = ObjectID.for_task_return(creation_task_id, 0)
        # Permanent pin (not seal-cleared): restarts RE-seal this oid,
        # so it must never be freed/tombstoned while the actor lives;
        # _finish_actor_removal drops the pin and the store entry.
        self.refs.add_seal_pin(creation_oid)
        shell_cls = (_ProcessActorShell
                     if (self.worker_pool is not None
                         or (alloc.node is not None
                             and alloc.node.agent is not None))
                     else _ActorShell)
        shell = shell_cls(self, actor_id, cls, args, kwargs, options,
                          creation_oid, alloc)
        shell.creation_task_id = creation_task_id
        self.events.record(
            creation_task_id.hex(), _ev.PENDING_NODE_ASSIGNMENT,
            name=f"{cls.__name__}.__init__", type=_ev.ACTOR_CREATION_TASK,
            job_id=self.job_id.hex(), actor_id=actor_id.hex(),
            node_id=(alloc.node.node_id.hex() if alloc.node else None),
            required_resources=demand,
        )
        # Persist the creation spec so a restarted driver can replay it
        # (parity: detached actors in the GCS actor table).  Serialized
        # BEFORE registration: an unpicklable constructor arg must not
        # leave a ghost registration behind (thread-mode actors never
        # pickle their args otherwise) — it just isn't persisted.
        spec_blob = None
        if (options.lifetime == "detached" and options.name
                and self._persist is not None):
            import cloudpickle as _cp

            try:
                spec_blob = _cp.dumps((cls, args, kwargs, options))
            except Exception:
                spec_blob = None
        # Register before starting: if __init__ fails instantly, the death
        # path must find (and unregister) the actor, or its name leaks.
        with self._lock:
            self._actors[actor_id] = shell
            if options.name:
                self._named_actors[options.name] = actor_id
            if alloc.node is not None:
                alloc.node.actor_ids.add(actor_id)
            if spec_blob is not None:
                self._detached_specs[options.name] = spec_blob
        if spec_blob is not None:
            self._mark_gcs_dirty()
        self.pubsub.publish("actor", {
            "event": "created", "actor_id": actor_id.hex(),
            "name": options.name or "", "class": cls.__name__,
        })
        shell.start()
        return shell, ObjectRef(creation_oid)

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args: tuple, kwargs: dict,
                          num_returns: Any = 1,
                          trace_ctx: Optional[Dict[str, str]] = None,
                          concurrency_group: Optional[str] = None):
        with self._lock:
            shell = self._actors.get(actor_id)
        task_id = TaskID.of(actor_id)
        streaming = num_returns == "streaming"
        return_ids = [] if streaming else [
            ObjectID.for_task_return(task_id, i) for i in range(num_returns)
        ]
        self._pin_returns(return_ids)
        if shell is None:
            err = ActorDiedError(actor_id.hex(), "no such actor")
            for oid in return_ids:
                self.store.put_error(oid, err)
            if streaming:
                self.store.put_error(
                    ObjectID.for_task_return(task_id, 0), err
                )
        else:
            self.events.record(
                task_id.hex(), _ev.SUBMITTED_TO_WORKER,
                name=f"{shell.cls.__name__}.{method_name}",
                type=_ev.ACTOR_TASK, job_id=self.job_id.hex(),
                actor_id=actor_id.hex(),
            )
            shell.submit(method_name, args, kwargs, return_ids, num_returns,
                         task_id,
                         trace_ctx if trace_ctx is not None
                         else _tracing().capture_context(),
                         concurrency_group=concurrency_group)
        if streaming:
            from ray_tpu.core.generator import ObjectRefGenerator

            return ObjectRefGenerator(task_id)
        return [ObjectRef(oid) for oid in return_ids]

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        with self._lock:
            shell = self._actors.get(actor_id)
        if shell is not None:
            if no_restart:
                shell.restarts_left = 0
            shell.kill(no_restart)

    def get_named_actor(self, name: str) -> ActorID:
        with self._lock:
            actor_id = self._named_actors.get(name)
        if actor_id is None:
            raise ValueError(f"no actor named {name!r}")
        return actor_id

    def named_actor_handle(self, name: str):
        """(actor_id, class name, @method num_returns table, @method
        concurrency-group table) for handle re-hydration — the same
        lookup worker processes do over RPC."""
        from ray_tpu.core.actor import (
            collect_method_cgroups,
            collect_method_num_returns,
        )

        actor_id = self.get_named_actor(name)
        with self._lock:
            shell = self._actors.get(actor_id)
        return (
            actor_id,
            shell.cls.__name__ if shell else "unknown",
            collect_method_num_returns(shell.cls) if shell else {},
            collect_method_cgroups(shell.cls) if shell else {},
        )

    def _on_actor_death(self, shell: _ActorShell):
        # Restart (parity: GCS actor FSM RESTARTING→ALIVE, gcs.proto actor
        # states): keep id + queue, re-construct the instance on a fresh
        # thread.  If the actor's node died, re-place it on a live node.
        # Explicit kills and creation failures don't restart.
        restartable = (
            shell.restarts_left > 0
            and not shell.no_restart
            and not shell.death_reason.startswith("creation")
        )
        node_died = shell.death_reason == "node died"
        strategy = shell.options.effective_strategy()
        if restartable and node_died:
            # Hard affinity to a dead node can never be satisfied
            # (parity: NodeAffinitySchedulingStrategy hard + node death
            # → actor unschedulable, fails permanently).
            if (isinstance(strategy, NodeAffinitySchedulingStrategy)
                    and not strategy.soft):
                want = (strategy.node_id.hex()
                        if isinstance(strategy.node_id, NodeID)
                        else str(strategy.node_id))
                with self._lock:
                    target = next((n for n in self._nodes.values()
                                   if n.node_id.hex() == want), None)
                if target is None or not target.alive:
                    restartable = False
        if restartable:
            shell.restarts_left -= 1
            if node_died:
                try:
                    alloc = self._try_allocate(
                        shell.options.resource_demand(), strategy
                    )
                except ValueError:
                    alloc = None
                    restartable = False  # e.g. PG was removed
                if restartable and alloc is None:
                    # Stay in RESTARTING until capacity appears (parity:
                    # GCS keeps the actor pending-recreation).
                    self._await_restart_capacity(shell, strategy)
                    return
                if restartable:
                    shell.allocation = alloc
                    with self._lock:
                        if alloc.node is not None:
                            alloc.node.actor_ids.add(shell.actor_id)
            if restartable:
                with shell._submit_gate:
                    shell.dead = False
                    shell.death_reason = ""
                    shell._drained = False
                shell.start()
                return
        if not node_died:
            shell.allocation.release()
        self._finish_actor_removal(shell)

    def _await_restart_capacity(self, shell: _ActorShell, strategy: Any):
        """Background wait for cluster capacity to restart a displaced
        actor; the handle keeps working once it comes back."""

        def poll():
            import time

            while not self._shutdown:
                try:
                    alloc = self._try_allocate(
                        shell.options.resource_demand(), strategy
                    )
                except ValueError:
                    self._finish_actor_removal(shell)
                    return
                if alloc is not None:
                    shell.allocation = alloc
                    with self._lock:
                        if alloc.node is not None:
                            alloc.node.actor_ids.add(shell.actor_id)
                    with shell._submit_gate:
                        shell.dead = False
                        shell.death_reason = ""
                        shell._drained = False
                    shell.start()
                    return
                time.sleep(0.05)

        threading.Thread(target=poll, daemon=True,
                         name=f"restart-{shell.actor_id.hex()[:8]}").start()

    def _actor_row(self, shell: _ActorShell, state: str) -> Dict[str, Any]:
        return {
            "actor_id": shell.actor_id.hex(),
            "class_name": shell.cls.__name__,
            "state": state,
            "name": shell.options.name or "",
            "node_id": (shell.node_id.hex() if shell.node_id else None),
            "death_cause": shell.death_reason or None,
            "job_id": self.job_id.hex(),
        }

    def _finish_actor_removal(self, shell: _ActorShell):
        self.pubsub.publish("actor", {
            "event": "died", "actor_id": shell.actor_id.hex(),
            "name": shell.options.name or "",
            "class": shell.cls.__name__,
            "reason": shell.death_reason or "",
        })
        # Drop the creation oid's permanent pin (its error/None value
        # stays readable through any still-held handles; the pin removal
        # lets it free once those drop).
        self.refs.remove_seal_pin(shell._creation_oid)
        # Stop a dead async actor's event loop thread (queued callbacks
        # — including cancellation dones — run before the stop lands).
        loop = getattr(shell, "_loop", None)
        if loop is not None:
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass  # already stopped/closed
        with self._lock:
            self._dead_actors.append(self._actor_row(shell, "DEAD"))
            self._actors.pop(shell.actor_id, None)
            if shell.allocation.node is not None:
                shell.allocation.node.actor_ids.discard(shell.actor_id)
            dropped_spec = False
            for name, aid in list(self._named_actors.items()):
                if aid == shell.actor_id:
                    del self._named_actors[name]
                    # A detached actor that truly died (kill/crash out
                    # of restarts) leaves the durable table too — but a
                    # driver SHUTDOWN must keep the spec so the next
                    # driver can replay it.
                    if not self._shutdown and name in self._detached_specs:
                        del self._detached_specs[name]
                        dropped_spec = True
        if dropped_spec:
            self._mark_gcs_dirty()
        self._retry_pending_pgs()
        self._notify()

    # -- placement groups --------------------------------------------------

    def create_placement_group(self, bundles: List[Dict[str, float]],
                               strategy: str, name: str,
                               lifetime: Optional[str]) -> PlacementGroup:
        pg_id = PlacementGroupID.of(self.job_id)
        pg = PlacementGroup(pg_id, bundles, strategy, name)
        ready_task = TaskID(pg_id.binary() + b"\x00" * 8)
        ready_oid = ObjectID.for_task_return(ready_task, 0)
        st = _PGState(
            pg=pg,
            bundles=[Bundle(i, dict(spec)) for i, spec in enumerate(bundles)],
            ready_oid=ready_oid,
            lifetime=lifetime,
        )
        # Permanent pin: ready() can be called repeatedly for the PG's
        # lifetime; remove_placement_group drops pin + store entry.
        self.refs.add_seal_pin(ready_oid)
        with self._lock:
            self._pgs[pg_id] = st
            if name:
                if name in self._named_pgs:
                    raise ValueError(f"placement group name {name!r} taken")
                self._named_pgs[name] = pg_id
        self._reserve_bundles(st, st.bundles)
        if lifetime == "detached" and name:
            self._mark_gcs_dirty()
        return pg

    def _retry_pending_pgs(self) -> None:
        """Capacity freed (PG/actor removal): pending placement groups
        get another shot (parity: GcsPlacementGroupManager retrying on
        resource updates, not just node adds)."""
        with self._lock:
            pending = [s for s in self._pgs.values()
                       if not s.removed
                       and any(b.node_id is None for b in s.bundles)]
        for s in pending:
            self._reserve_bundles(
                s, [b for b in s.bundles if b.node_id is None]
            )

    def _reserve_bundles(self, st: _PGState, bundles: List[Bundle]) -> bool:
        """Reserve bundles on nodes per the PG strategy.  All-or-nothing
        with rollback (parity: the 2-phase commit in
        gcs_placement_group_scheduler.cc, simplified to one process)."""
        with self._pg_reserve_lock:
            if st.removed:  # raced with remove_placement_group
                return False
            bundles = [b for b in bundles if b.node_id is None]
            if not bundles:
                return True
            return self._reserve_bundles_locked(st, bundles)

    def _reserve_bundles_locked(self, st: _PGState,
                                bundles: List[Bundle]) -> bool:
        strategy = st.pg.strategy
        nodes = self._alive_nodes()
        # Nodes already holding this PG's surviving bundles — STRICT_SPREAD
        # re-reservation must not collapse onto them.
        occupied = {b.node_id for b in st.bundles if b.node_id is not None}
        # ICI-aware ordering: nodes labeled with an integer "ici_index"
        # are considered in coordinate order so PACKed bundles land on a
        # contiguous slice block.
        def ici_key(n: NodeState):
            try:
                return (0, int(n.labels.get("ici_index", "")))
            except ValueError:
                return (1, 0)

        nodes = sorted(nodes, key=ici_key)
        reserved: List[Tuple[Bundle, NodeState]] = []

        def rollback():
            for b, n in reserved:
                n.pool.release(b.resources)
                with b.lock:
                    b.node_id = None
                    b.available = {}
            reserved.clear()

        def place_on(b: Bundle, n: NodeState) -> bool:
            if n.pool.try_acquire(b.resources):
                with b.lock:
                    b.node_id = n.node_id
                    b.available = dict(b.resources)
                reserved.append((b, n))
                return True
            return False

        if strategy == "ICI_CONTIGUOUS":
            # Gang placement on a contiguous axis-aligned sub-grid of
            # ONE slice's ICI torus (SURVEY.md §7 hard part 4; extends
            # the reference's bundle policies
            # raylet/scheduling/policy/bundle_scheduling_policy.h:31-98
            # with slice topology — the reference only sketches TPU
            # head resources in _private/accelerator.py:176-191).
            # Fragmented placements are REJECTED: the group stays
            # pending until a whole rectangle frees up.  Node death
            # voids the whole gang (re-reservation re-places every
            # bundle so adjacency is preserved).
            requested = {id(b) for b in bundles}
            voided = [b for b in st.bundles
                      if b.node_id is not None and id(b) not in requested]
            if voided:
                for b in voided:
                    node = self._nodes.get(b.node_id)
                    with b.lock:
                        avail = dict(b.available)
                        b.available = {}
                        b.node_id = None
                    if node is not None and node.alive:
                        node.pool.release(avail)
                bundles = list(st.bundles)
            return self._reserve_ici_contiguous(st, bundles, nodes,
                                                place_on, rollback)

        if strategy in ("PACK", "STRICT_PACK"):
            # Try to land everything on a single node first.
            for n in nodes:
                ok = True
                for b in bundles:
                    if not place_on(b, n):
                        ok = False
                        break
                if ok:
                    self._pg_maybe_ready(st)
                    return True
                rollback()
                reserved.clear()
            if strategy == "STRICT_PACK":
                return False  # stays pending; bundles unreserved
            # soft PACK: greedy first-fit across nodes
            for b in bundles:
                if not any(place_on(b, n) for n in nodes):
                    rollback()
                    return False
            self._pg_maybe_ready(st)
            return True

        # SPREAD / STRICT_SPREAD: distinct nodes (best-effort for SPREAD).
        used: set = set(occupied)
        for b in bundles:
            placed = False
            for n in nodes:
                if n.node_id in used:
                    continue
                if place_on(b, n):
                    used.add(n.node_id)
                    placed = True
                    break
            if not placed and strategy == "SPREAD":
                for n in nodes:
                    if place_on(b, n):
                        placed = True
                        break
            if not placed:
                rollback()
                return False
        self._pg_maybe_ready(st)
        return True

    def _reserve_ici_contiguous(self, st: _PGState, bundles: List[Bundle],
                                nodes: List[NodeState], place_on,
                                rollback) -> bool:
        """Place n bundles on an h×w rectangle of ici_coord-labeled
        nodes within one slice, row-major bundle order (bundle index →
        mesh position is deterministic, so callers can map coordinates
        to mesh axes).  All-or-nothing."""
        n = len(bundles)
        # Slice name → {(x, y): node}
        slices: Dict[str, Dict[Tuple[int, int], NodeState]] = {}
        for node in nodes:
            coord = node.labels.get("ici_coord")
            if not coord:
                continue
            try:
                x, y = (int(c) for c in coord.split(","))
            except ValueError:
                continue
            key = node.labels.get("raytpu.io/tpu-slice",
                                  node.labels.get("raytpu.io/tpu-pod", ""))
            slices.setdefault(key, {})[(x, y)] = node

        def shapes():
            # Prefer squares, then squat rectangles (less ICI hop
            # diameter); 1×n last.
            out = []
            for h in range(int(n ** 0.5), 0, -1):
                if n % h == 0:
                    out.append((h, n // h))
                    if h != n // h:
                        out.append((n // h, h))
            return out

        for grid in slices.values():
            if len(grid) < n:
                continue
            xs = [c[0] for c in grid]
            ys = [c[1] for c in grid]
            for h, w in shapes():
                for x0 in range(min(xs), max(xs) - h + 2):
                    for y0 in range(min(ys), max(ys) - w + 2):
                        cells = [(x0 + i, y0 + j)
                                 for i in range(h) for j in range(w)]
                        if any(c not in grid for c in cells):
                            continue
                        ok = True
                        for b, c in zip(bundles, cells):
                            if not place_on(b, grid[c]):
                                ok = False
                                break
                        if ok:
                            self._pg_maybe_ready(st)
                            return True
                        rollback()
        return False  # no contiguous window — stays pending

    def _pg_maybe_ready(self, st: _PGState):
        if all(b.node_id is not None for b in st.bundles):
            if not self.store.contains(st.ready_oid):
                self.store.put_value(st.ready_oid, None)

    def pg_ready_ref(self, pg_id: PlacementGroupID) -> ObjectRef:
        with self._lock:
            st = self._pgs.get(pg_id)
        if st is None:
            raise ValueError("unknown placement group")
        return ObjectRef(st.ready_oid)

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        with self._pg_reserve_lock:
            with self._lock:
                st = self._pgs.get(pg_id)
                if st is None or st.removed:
                    return
                st.removed = True
                if st.pg.name:
                    self._named_pgs.pop(st.pg.name, None)
            # Return only the *unused* part of each reservation now; the
            # in-use part comes back when each holder finishes (see
            # _Allocation.release) — never oversubscribe the node.
            bundle_set = set(map(id, st.bundles))
            for b in st.bundles:
                if b.node_id is not None:
                    node = self._nodes.get(b.node_id)
                    with b.lock:
                        unused = dict(b.available)
                        b.available = {}
                        b.node_id = None  # atomic with the ledger zeroing
                    if node is not None and node.alive:
                        node.pool.release(unused)
        # Kill actors living inside the group (parity: PG removal kills
        # the actors/tasks scheduled into it).
        with self._lock:
            doomed = [s for s in self._actors.values()
                      if s.allocation.bundle is not None
                      and id(s.allocation.bundle) in bundle_set]
        for shell in doomed:
            shell.restarts_left = 0
            shell.kill(no_restart=True)
        # Drop the ready marker's permanent pin + store entry.  The
        # tombstone turns a get on a still-held pg.ready() ref into
        # ObjectFreedError instead of an unseal-forever hang.
        self.refs.remove_seal_pin(st.ready_oid)
        self.store.release(st.ready_oid, tombstone=True)
        self._mark_gcs_dirty()
        self._retry_pending_pgs()
        self._notify()

    def get_named_placement_group(self, name: str) -> PlacementGroup:
        with self._lock:
            pg_id = self._named_pgs.get(name)
            if pg_id is None:
                raise ValueError(f"no placement group named {name!r}")
            return self._pgs[pg_id].pg

    def placement_group_table(self) -> Dict[str, Any]:
        with self._lock:
            out = {}
            for pg_id, st in self._pgs.items():
                out[pg_id.hex()] = {
                    "strategy": st.pg.strategy,
                    "name": st.pg.name,
                    "state": ("REMOVED" if st.removed else
                              "CREATED" if all(b.node_id is not None
                                               for b in st.bundles)
                              else "PENDING"),
                    "bundles": {
                        b.index: (b.node_id.hex() if b.node_id else None)
                        for b in st.bundles
                    },
                }
            return out

    # -- cluster info ------------------------------------------------------

    def actor_table(self) -> List[Dict[str, Any]]:
        """Live + dead actor entries (parity: GCS ActorTableData rows
        behind `ray list actors`, gcs.proto actor FSM states)."""
        with self._lock:
            live = []
            for shell in self._actors.values():
                if not shell.dead:
                    state = "ALIVE" if shell.instance is not None \
                        else "PENDING_CREATION"
                else:
                    state = "RESTARTING"
                live.append(self._actor_row(shell, state))
            return live + list(self._dead_actors)

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self._alive_nodes():
            for k, v in n.pool.total.items():
                out[k] = out.get(k, 0) + v
        return out

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self._alive_nodes():
            with n.pool._lock:
                for k, v in n.pool.available.items():
                    out[k] = out.get(k, 0) + v
        return out

    def nodes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{
                "NodeID": nid.hex(),
                "Alive": self._nodes[nid].alive,
                "Resources": dict(self._nodes[nid].pool.total),
                "Labels": dict(self._nodes[nid].labels),
            } for nid in self._node_order]

    # -- log plane ---------------------------------------------------------

    def _publish_local_logs(self, file: str, lines: List[str],
                            truncated: bool = False) -> None:
        self.ingest_logs("head", file, lines, truncated=truncated)

    def ingest_logs(self, node: str, file: str,
                    lines: List[str], truncated: bool = False) -> None:
        """One batch of worker log lines into the head buffer (+ echo
        to the driver console — parity: ray's log_to_driver prefixing
        lines with their producing worker/node).  ``truncated`` marks a
        stream whose file was rotated/truncated mid-tail (these lines
        are a readable suffix)."""
        self.logs.ingest(node, file, lines, truncated=truncated)
        # Publish only once someone has pulled the channel: with no
        # subscriber the ring would duplicate LogBuffer's retention and
        # every batch would wake all other channels' waiters for nothing.
        if self.pubsub.has_consumers("logs"):
            self.pubsub.publish("logs", {"node": node, "file": file,
                                         "lines": list(lines)})
        from ray_tpu.utils.config import get_config

        if get_config().log_to_driver:
            tag = file.rsplit(".", 1)[0]
            where = f"{tag}" if node == "head" else f"{tag}, node={node[:8]}"
            for ln in lines:
                # Gloo's per-rank connection chatter ("[Gloo] Rank N is
                # connected to M peer ranks...") floods the driver
                # console quadratically on multi-process dryruns; keep
                # it out of the echo only — LogBuffer retains every
                # line for `raytpu logs`.
                if _GLOO_CONNECT_RE.search(ln):
                    continue
                print(f"({where}) {ln}", flush=True)

    def shutdown(self):
        from ray_tpu.core import object_ref as _object_ref

        # Stop counting first: mass ref destruction during teardown must
        # not trigger frees against a closing store.
        self.refs.close()
        if _object_ref._ref_hooks == self._ref_hooks:
            _object_ref.clear_ref_hooks()
        with self._dispatch_cv:
            self._shutdown = True
            self._dispatch_cv.notify_all()
        with self._lock:
            actors = list(self._actors.values())
        for shell in actors:
            shell.restarts_left = 0
            shell.kill()
        # Ask joined node daemons to exit (best-effort cast), then drop
        # their channels.
        with self._lock:
            agents = [n.agent for n in self._nodes.values()
                      if n.agent is not None]
        for agent in agents:
            agent.shutdown_daemon()
        if self.worker_pool is not None:
            self.worker_pool.shutdown()
        # Drop the federated per-process metric snapshots (they ride
        # worker replies, see apply_ref_batches): those processes are
        # gone, so their series would otherwise show up as stale
        # samples in the NEXT cluster's /metrics scrape forever.
        from ray_tpu.util import metrics as _metrics

        _metrics.clear_remote()
        # Same for the telemetry history plane: stop the driver's
        # sampler and drop every ring (local + federated) so the next
        # runtime in this process starts from an empty plane.
        from ray_tpu.util import timeseries as _timeseries

        _timeseries.shutdown()
        if self._log_monitor is not None:
            # AFTER the pool: stop()'s final sweep then sees everything
            # the dying workers flushed.
            self._log_monitor.stop()
        if self._persist is not None:
            # Final snapshot AFTER actor teardown (specs were kept —
            # _finish_actor_removal skips spec removal once _shutdown).
            self._persist.close(final_flush=True)
        self._exec_pool.close()
        self.store.close()
