"""Local runtime: tasks, actors, objects in one process.

This is the single-process implementation of the runtime interface —
semantics-first parity with the reference's core: dependency-aware task
dispatch (ray: raylet/local_task_manager.cc WaitForTaskArgsRequests /
DispatchScheduledTasksToWorkers), logical resource accounting
(common/scheduling/resource_instance_set.cc), per-actor ordered
execution queues (core_worker/transport/actor_scheduling_queue.cc),
error capture + retries (core_worker/task_manager.h max_retries), and
named actors (gcs actor directory).

The multi-process node runtime (ray_tpu.core.node) reuses the same
dispatch logic with workers behind an RPC boundary and the C++
shared-memory store; libraries only ever see the api module, so they
run unchanged on either.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import queue as _queue
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core.exceptions import (
    ActorDiedError,
    TaskError,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.store import LocalObjectStore
from ray_tpu.utils.config import get_config
from ray_tpu.utils.ids import ActorID, JobID, ObjectID, TaskID


@dataclasses.dataclass
class TaskOptions:
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    num_returns: int = 1
    max_retries: int = 0
    name: str = ""
    placement_group: Any = None
    placement_bundle_index: int = -1

    def resource_demand(self) -> Dict[str, float]:
        demand = dict(self.resources)
        if self.num_cpus:
            demand["CPU"] = demand.get("CPU", 0) + self.num_cpus
        if self.num_tpus:
            demand["TPU"] = demand.get("TPU", 0) + self.num_tpus
        return demand


@dataclasses.dataclass
class ActorOptions:
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    name: Optional[str] = None
    get_if_exists: bool = False
    max_restarts: int = 0
    max_concurrency: int = 1
    lifetime: Optional[str] = None  # None | "detached"
    placement_group: Any = None
    placement_bundle_index: int = -1

    def resource_demand(self) -> Dict[str, float]:
        demand = dict(self.resources)
        if self.num_cpus:
            demand["CPU"] = demand.get("CPU", 0) + self.num_cpus
        if self.num_tpus:
            demand["TPU"] = demand.get("TPU", 0) + self.num_tpus
        return demand


class ResourcePool:
    """Logical resource ledger (parity: NodeResourceInstanceSet)."""

    def __init__(self, total: Dict[str, float]):
        self._lock = threading.Lock()
        self.total = dict(total)
        self.available = dict(total)
        self.cv = threading.Condition(self._lock)

    def can_fit(self, demand: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0) >= v for k, v in demand.items())

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        with self._lock:
            if all(self.available.get(k, 0) >= v - 1e-9 for k, v in demand.items()):
                for k, v in demand.items():
                    self.available[k] = self.available.get(k, 0) - v
                return True
            return False

    def release(self, demand: Dict[str, float]) -> None:
        with self.cv:
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0) + v
            self.cv.notify_all()


@dataclasses.dataclass
class _PendingTask:
    fn: Callable
    args: tuple
    kwargs: dict
    options: TaskOptions
    return_ids: List[ObjectID]
    retries_left: int
    task_id: TaskID
    function_name: str


class _ActorShell:
    """Server side of one actor: instance + ordered execution thread
    (parity: ActorSchedulingQueue ordering guarantee)."""

    def __init__(self, runtime: "LocalRuntime", actor_id: ActorID, cls: type,
                 args: tuple, kwargs: dict, options: ActorOptions,
                 creation_oid: ObjectID):
        self.runtime = runtime
        self.actor_id = actor_id
        self.cls = cls
        self.init_args = args
        self.init_kwargs = kwargs
        self.options = options
        self.instance: Any = None
        self.dead = False
        self.death_reason = ""
        self.no_restart = False  # set by an explicit kill(no_restart=True)
        self.restarts_left = options.max_restarts
        self.queue: _queue.Queue = _queue.Queue()
        self._creation_oid = creation_oid
        self.thread: Optional[threading.Thread] = None

    def start(self):
        """Called after the runtime has registered the actor, so death
        bookkeeping always sees a registered actor."""
        self.thread = threading.Thread(
            target=self._run, name=f"actor-{self.actor_id.hex()[:8]}",
            daemon=True,
        )
        self.thread.start()

    def _construct(self):
        self.instance = self.cls(*self.init_args, **self.init_kwargs)

    def _run(self):
        # Actor creation is the first "task" (parity: actor creation task).
        try:
            self._construct()
            self.runtime.store.put_value(self._creation_oid, None)
        except BaseException as e:
            self.dead = True
            self.death_reason = f"creation failed: {e!r}"
            self.runtime.store.put_error(
                self._creation_oid,
                ActorDiedError(repr(self.cls), self.death_reason),
            )
            self.runtime._on_actor_death(self)
            return
        while True:
            item = self.queue.get()
            if item is None:  # kill signal
                break
            method_name, args, kwargs, return_ids, num_returns = item
            try:
                resolved_args, resolved_kwargs = self.runtime.resolve_args(
                    args, kwargs
                )
                method = getattr(self.instance, method_name)
                result = method(*resolved_args, **resolved_kwargs)
                import inspect

                if inspect.iscoroutine(result):
                    import asyncio

                    result = asyncio.run(result)
                self.runtime._store_results(result, return_ids, num_returns)
            except BaseException as e:
                err = TaskError(f"{self.cls.__name__}.{method_name}", e)
                for oid in return_ids:
                    self.runtime.store.put_error(oid, err)
                if not isinstance(e, Exception):
                    # actor thread dies on SystemExit et al
                    self.dead = True
                    self.death_reason = repr(e)
                    break
        self._drain(ActorDiedError(repr(self.cls), self.death_reason or "killed"))
        self.runtime._on_actor_death(self)

    def _drain(self, err: BaseException):
        while True:
            try:
                item = self.queue.get_nowait()
            except _queue.Empty:
                return
            if item is None:
                continue
            for oid in item[3]:
                self.runtime.store.put_error(oid, err)

    def submit(self, method_name: str, args, kwargs, return_ids, num_returns):
        if self.dead:
            err = ActorDiedError(repr(self.cls), self.death_reason or "dead")
            for oid in return_ids:
                self.runtime.store.put_error(oid, err)
            return
        self.queue.put((method_name, args, kwargs, return_ids, num_returns))

    def kill(self, no_restart: bool = True):
        self.dead = True
        self.no_restart = no_restart
        self.death_reason = "killed via ray_tpu.kill"
        self.queue.put(None)


class LocalRuntime:
    def __init__(self, *, resources: Optional[Dict[str, float]] = None,
                 job_id: Optional[JobID] = None):
        cfg = get_config()
        total = dict(resources or {})
        if "CPU" not in total:
            total["CPU"] = float(cfg.num_workers_soft_limit or 8)
        total.setdefault("memory", 64 * 1024**3)
        self.resources_total = total
        self.pool = ResourcePool(total)
        self.store = LocalObjectStore()
        self.job_id = job_id or JobID.next()
        self.driver_task_id = TaskID.for_driver(self.job_id)
        self._put_counter = itertools.count(1)
        self._lock = threading.Lock()
        self._pending: List[_PendingTask] = []
        self._dispatch_cv = threading.Condition()
        self._shutdown = False
        self._actors: Dict[ActorID, _ActorShell] = {}
        self._named_actors: Dict[str, ActorID] = {}
        self._running_tasks = 0
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="dispatcher", daemon=True
        )
        self._dispatcher.start()

    # -- objects -----------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_put(self.driver_task_id, next(self._put_counter))
        self.store.put_value(oid, value)
        return ObjectRef(oid)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        out = [self.store.get(r.id, timeout) for r in ref_list]
        return out[0] if single else out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True):
        ids = [r.id for r in refs]
        ready_ids, pending_ids = self.store.wait(ids, num_returns, timeout)
        by_id = {r.id: r for r in refs}
        return [by_id[i] for i in ready_ids], [by_id[i] for i in pending_ids]

    def resolve_args(self, args: tuple, kwargs: dict) -> Tuple[tuple, dict]:
        """Replace top-level ObjectRef args with their values
        (parity: LocalDependencyResolver inlining)."""

        def res(v):
            return self.get(v) if isinstance(v, ObjectRef) else v

        return tuple(res(a) for a in args), {k: res(v) for k, v in kwargs.items()}

    def _deps_ready(self, args: tuple, kwargs: dict) -> bool:
        for v in list(args) + list(kwargs.values()):
            if isinstance(v, ObjectRef) and not self.store.contains(v.id):
                return False
        return True

    def _store_results(self, result: Any, return_ids: List[ObjectID],
                       num_returns: int):
        if num_returns == 1:
            self.store.put_value(return_ids[0], result)
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(values)} values"
                )
            for oid, v in zip(return_ids, values):
                self.store.put_value(oid, v)

    # -- tasks -------------------------------------------------------------

    def submit_task(self, fn: Callable, args: tuple, kwargs: dict,
                    options: TaskOptions) -> List[ObjectRef]:
        demand = options.resource_demand()
        if not self.pool.can_fit(demand):
            raise ValueError(
                f"task {fn.__name__!r} demands {demand}, cluster total is "
                f"{self.pool.total} — infeasible"
            )
        task_id = TaskID.of(ActorID.nil_for_job(self.job_id))
        return_ids = [
            ObjectID.for_task_return(task_id, i)
            for i in range(options.num_returns)
        ]
        pt = _PendingTask(
            fn=fn, args=args, kwargs=kwargs, options=options,
            return_ids=return_ids, retries_left=options.max_retries,
            task_id=task_id, function_name=getattr(fn, "__name__", repr(fn)),
        )
        with self._dispatch_cv:
            self._pending.append(pt)
            self._dispatch_cv.notify_all()
        return [ObjectRef(oid) for oid in return_ids]

    def _dispatch_loop(self):
        while True:
            with self._dispatch_cv:
                while not self._shutdown:
                    runnable = self._next_runnable_locked()
                    if runnable is not None:
                        break
                    self._dispatch_cv.wait(0.02)
                if self._shutdown:
                    return
            self._start_task(runnable)

    def _next_runnable_locked(self) -> Optional[_PendingTask]:
        for pt in self._pending:
            if not self._deps_ready(pt.args, pt.kwargs):
                continue
            if self.pool.try_acquire(pt.options.resource_demand()):
                self._pending.remove(pt)
                return pt
        return None

    def _start_task(self, pt: _PendingTask):
        def run():
            try:
                args, kwargs = self.resolve_args(pt.args, pt.kwargs)
                result = pt.fn(*args, **kwargs)
                self._store_results(result, pt.return_ids, pt.options.num_returns)
            except Exception as e:
                if pt.retries_left > 0:
                    pt.retries_left -= 1
                    with self._dispatch_cv:
                        self._pending.append(pt)
                        self._dispatch_cv.notify_all()
                else:
                    err = e if isinstance(e, TaskError) else TaskError(
                        pt.function_name, e
                    )
                    for oid in pt.return_ids:
                        self.store.put_error(oid, err)
            finally:
                self.pool.release(pt.options.resource_demand())
                with self._dispatch_cv:
                    self._dispatch_cv.notify_all()

        threading.Thread(
            target=run, name=f"task-{pt.function_name}", daemon=True
        ).start()

    # -- actors ------------------------------------------------------------

    def create_actor(self, cls: type, args: tuple, kwargs: dict,
                     options: ActorOptions):
        if options.name:
            with self._lock:
                existing = self._named_actors.get(options.name)
                shell = self._actors.get(existing) if existing else None
            if shell is not None:
                if options.get_if_exists:
                    return shell, ObjectRef(shell._creation_oid)
                raise ValueError(f"actor name {options.name!r} already taken")
        demand = options.resource_demand()
        if not self.pool.can_fit(demand):
            raise ValueError(
                f"actor {cls.__name__!r} demands {demand}, cluster total is "
                f"{self.pool.total} — infeasible"
            )
        # Actors hold their resources for their lifetime.
        while not self.pool.try_acquire(demand):
            with self.pool.cv:
                self.pool.cv.wait(0.05)
        actor_id = ActorID.of(self.job_id)
        creation_oid = ObjectID.for_task_return(TaskID.of(actor_id), 0)
        shell = _ActorShell(self, actor_id, cls, args, kwargs, options,
                            creation_oid)
        # Register before starting: if __init__ fails instantly, the death
        # path must find (and unregister) the actor, or its name leaks.
        with self._lock:
            self._actors[actor_id] = shell
            if options.name:
                self._named_actors[options.name] = actor_id
        shell.start()
        return shell, ObjectRef(creation_oid)

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args: tuple, kwargs: dict,
                          num_returns: int = 1) -> List[ObjectRef]:
        with self._lock:
            shell = self._actors.get(actor_id)
        task_id = TaskID.of(actor_id)
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(num_returns)]
        if shell is None:
            err = ActorDiedError(actor_id.hex(), "no such actor")
            for oid in return_ids:
                self.store.put_error(oid, err)
        else:
            shell.submit(method_name, args, kwargs, return_ids, num_returns)
        return [ObjectRef(oid) for oid in return_ids]

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        with self._lock:
            shell = self._actors.get(actor_id)
        if shell is not None:
            if no_restart:
                shell.restarts_left = 0
            shell.kill(no_restart)

    def get_named_actor(self, name: str) -> ActorID:
        with self._lock:
            actor_id = self._named_actors.get(name)
        if actor_id is None:
            raise ValueError(f"no actor named {name!r}")
        return actor_id

    def _on_actor_death(self, shell: _ActorShell):
        # Restart-in-place (parity: GCS actor FSM RESTARTING→ALIVE,
        # gcs.proto actor states): keep id + queue, re-construct the
        # instance on a fresh thread.  Explicit kills and creation
        # failures don't restart.
        restartable = (
            shell.restarts_left > 0
            and not shell.no_restart
            and not shell.death_reason.startswith("creation")
        )
        if restartable:
            shell.restarts_left -= 1
            shell.dead = False
            shell.death_reason = ""
            shell.start()
            return
        self.pool.release(shell.options.resource_demand())
        with self._lock:
            self._actors.pop(shell.actor_id, None)
            for name, aid in list(self._named_actors.items()):
                if aid == shell.actor_id:
                    del self._named_actors[name]

    # -- cluster info ------------------------------------------------------

    def cluster_resources(self) -> Dict[str, float]:
        return dict(self.pool.total)

    def available_resources(self) -> Dict[str, float]:
        with self.pool._lock:
            return dict(self.pool.available)

    def nodes(self) -> List[Dict[str, Any]]:
        return [{
            "NodeID": "local",
            "Alive": True,
            "Resources": dict(self.pool.total),
        }]

    def shutdown(self):
        with self._dispatch_cv:
            self._shutdown = True
            self._dispatch_cv.notify_all()
        with self._lock:
            actors = list(self._actors.values())
        for shell in actors:
            shell.restarts_left = 0
            shell.kill()
