"""Worker process entry point + the worker-side runtime proxy.

Parity: the per-process core worker (ray:
src/ray/core_worker/core_worker.cc — ExecuteTask:2565, HandlePushTask:
3072) and its Python task-execution callback (python/ray/_raylet.pyx:
1448 execute_task).  A worker process:

1. connects back to the driver's AF_UNIX socket using the one-time
   spawn token (parity: worker registration with the raylet,
   node_manager.cc:1292),
2. receives the welcome payload (config snapshot, shared-memory arena
   name, job id),
3. installs a ``WorkerRuntime`` as the process-global runtime so that
   any ``ray_tpu`` API call made by user code inside a task — nested
   tasks, ``get``/``put``, actor creation — proxies to the driver's
   control plane (parity: CoreWorker SubmitTask from within a worker),
4. serves pushed work: plain tasks, actor construction, actor method
   calls, until told to exit or its driver hangs up.

Large values move through the C++ shared-memory store that the worker
attaches by name — reads are zero-copy (pinned views over the mapped
arena), writes land directly under the destination ObjectID so the
driver only learns ("shm", size), never the bytes.
"""

from __future__ import annotations

import collections
import contextlib
import os
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.wire import ChannelClosedError, MsgChannel, WireRef
from ray_tpu.utils.ids import ActorID, ObjectID, TaskID
from ray_tpu.utils.serialization import (
    deserialize_object,
    framed_size,
    serialize_parts,
    try_shm_put,
    write_framed,
)


class _RefClient:
    """Borrower-side reference reporting (parity: the borrower half of
    the ownership protocol, reference_count.h AddBorrowedObject /
    removing borrows on WaitForRefRemoved).  Every live ObjectRef in
    this worker counts one local ref; transitions 0→1 / 1→0 are batched
    and flushed to the owner as a single ``ref`` message.  Flush points:
    end of every task / actor method (synchronous — the add must land
    before the driver releases the task's argument pins) and a periodic
    background sweep for handles dropped by long-lived actor state."""

    def __init__(self, chan: MsgChannel):
        self._chan = chan
        # RLock: on_create/on_delete run from ObjectRef __init__/__del__;
        # cyclic GC triggered inside the critical section can re-enter
        # on the same thread (see ReferenceCounter._lock).
        self._lock = threading.RLock()
        # Serializes whole flushes (snapshot + send): without it the 1s
        # sweep and a task-end flush can deliver batches out of snapshot
        # order — an add overtaken by its del leaks the borrow forever.
        self._flush_lock = threading.Lock()
        self._local: Dict[bytes, int] = {}
        self._adds: set = set()
        self._dels: set = set()
        self._adopted: set = set()
        # (task_id_bin, from_index) stream releases deferred from
        # generator __del__ — sent by flush, never from GC context.
        self._stream_releases: "collections.deque" = collections.deque()

    def adopt(self, oid_bin: bytes) -> None:
        """The owner already registered our borrow (e.g. in the
        submit-task reply) — the first handle must not re-report it."""
        with self._lock:
            self._adopted.add(oid_bin)

    def on_create(self, oid) -> None:
        b = oid.binary()
        with self._lock:
            n = self._local.get(b, 0)
            self._local[b] = n + 1
            if n == 0:
                if b in self._adopted:
                    self._adopted.discard(b)  # owner-side count exists
                elif b in self._dels:
                    self._dels.discard(b)  # cancel the unsent del
                else:
                    self._adds.add(b)

    def on_delete(self, oid) -> None:
        b = oid.binary()
        with self._lock:
            n = self._local.get(b, 0)
            if n <= 1:
                self._local.pop(b, None)
                if b in self._adds:
                    self._adds.discard(b)  # never told the owner
                else:
                    self._dels.add(b)
            else:
                self._local[b] = n - 1

    def defer_stream_release(self, task_bin: bytes, index: int) -> None:
        self._stream_releases.append((task_bin, index))

    def drain_batches(self):
        """Snapshot pending add/del batches for piggybacking on a task
        reply — the owner applies adds BEFORE sealing/pinning the
        reply's results and dels AFTER, so a del of a ref that rides in
        the returned value can never beat its nested pin."""
        with self._flush_lock:
            with self._lock:
                adds, self._adds = self._adds, set()
                dels, self._dels = self._dels, set()
        return list(adds), list(dels)

    def flush(self) -> None:
        with self._flush_lock:
            with self._lock:
                adds, self._adds = self._adds, set()
                dels, self._dels = self._dels, set()
            streams = []
            while self._stream_releases:
                streams.append(self._stream_releases.popleft())
            try:
                if adds or dels:
                    self._chan.call("ref", add=list(adds), rem=list(dels))
                for task_bin, index in streams:
                    self._chan.call("release_stream", task=task_bin,
                                    index=index)
            except Exception:
                pass  # channel down → owner drops this worker's borrows


class _StoreProxy:
    """The subset of LocalObjectStore the generator/consumer paths use,
    proxied to the driver."""

    def __init__(self, wr: "WorkerRuntime"):
        self._wr = wr

    def wait(self, oids: List[ObjectID], num_returns: int,
             timeout: Optional[float]):
        ready, pending = self._wr._chan.call(
            "wait", oids=[o.binary() for o in oids],
            num_returns=num_returns, timeout=timeout,
        )
        return [ObjectID(b) for b in ready], [ObjectID(b) for b in pending]

    def peek_error(self, oid: ObjectID):
        return self._wr._chan.call("peek_error", oid=oid.binary())

    def contains(self, oid: ObjectID) -> bool:
        return self._wr._chan.call("contains", oid=oid.binary())

    def get(self, oid: ObjectID, timeout: Optional[float] = None):
        return self._wr._fetch([oid.binary()], timeout)[0]


class _KvProxy:
    def __init__(self, wr: "WorkerRuntime"):
        self._wr = wr

    def put(self, key, value, *, overwrite: bool = True, namespace=None):
        return self._wr._chan.call("kv_put", key=key, value=value,
                                   overwrite=overwrite, namespace=namespace)

    def get(self, key, *, namespace=None):
        return self._wr._chan.call("kv_get", key=key, namespace=namespace)

    def delete(self, key, *, namespace=None):
        return self._wr._chan.call("kv_del", key=key, namespace=namespace)

    def exists(self, key, *, namespace=None):
        return self._wr._chan.call("kv_exists", key=key,
                                   namespace=namespace)

    def keys(self, prefix=b"", *, namespace=None):
        return self._wr._chan.call("kv_keys", prefix=prefix,
                                   namespace=namespace)


class WorkerRuntime:
    """Driver-API facade inside a worker process (parity: the worker's
    CoreWorker — same surface as LocalRuntime for everything user code
    can reach, implemented as RPCs to the owner/driver)."""

    def __init__(self, chan: MsgChannel, shm, shm_threshold: int):
        self._chan = chan
        self._shm = shm
        self._shm_threshold = shm_threshold
        self.store = _StoreProxy(self)
        self.kv = _KvProxy(self)
        # Borrower-side ref reporting: every ObjectRef built in this
        # process registers with the owner so borrowed values stay
        # alive while we hold them.
        from ray_tpu.core import object_ref as _object_ref

        self.refs = _RefClient(chan)
        _object_ref.install_ref_hooks(self.refs.on_create,
                                      self.refs.on_delete)

    # -- objects -----------------------------------------------------------

    def _read_shm(self, oid_bin: bytes):
        """Deserialize one shared-arena object — zero-copy when this
        worker attached the arena (views stay pinned until GC'd).  An
        arena miss (object lives on another node, or was evicted) falls
        back to a get_raw through the host, which pulls/materializes it
        into the local arena; attach-failed workers always go through
        the host with inline bytes."""
        if self._shm is not None:
            try:
                pb = self._shm.get(oid_bin, timeout=0.05)
                return deserialize_object(pb.view)
            except OSError:
                pass  # not local (yet) — ask the host to make it so
        no_shm = self._shm is None
        (kind, payload), = self._chan.call("get_raw", oids=[oid_bin],
                                           no_shm=no_shm)
        if kind == "err":
            raise payload
        if kind == "shm":
            pb = self._shm.get(oid_bin, timeout=5.0)
            return deserialize_object(pb.view)
        return deserialize_object(payload)

    def _fetch(self, oid_bins: List[bytes],
               timeout: Optional[float] = None) -> List[Any]:
        entries = self._chan.call("get_raw", oids=oid_bins,
                                  timeout=timeout,
                                  no_shm=self._shm is None)
        out = []
        for b, (kind, payload) in zip(oid_bins, entries):
            if kind == "err":
                raise payload
            if kind == "shm":
                out.append(self._read_shm(b))
            else:
                out.append(deserialize_object(payload))
        return out

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        out = self._fetch([r.id.binary() for r in ref_list], timeout)
        return out[0] if single else out

    def put(self, value: Any) -> ObjectRef:
        from ray_tpu.core.object_ref import collect_nested_refs

        with collect_nested_refs() as nested:
            meta, buffers = serialize_parts(value)
        nested_bins = [o.binary() for o in nested]
        size = framed_size(meta, buffers)
        if self._shm is not None and size >= self._shm_threshold:
            oid_bin = self._chan.call("alloc_put_oid")
            self.refs.adopt(oid_bin)  # owner pre-registered our borrow
            sealed = try_shm_put(self._shm, oid_bin, meta, buffers, size)
            if sealed:
                # Outside the try: a ChannelClosedError here is a real
                # failure (the value IS in the arena), not arena-full.
                self._chan.call("mark_shm", oid=oid_bin, size=size,
                                nested=nested_bins)
                return ObjectRef(ObjectID(oid_bin))
            out = bytearray(size)
            write_framed(memoryview(out), meta, buffers)
            self._chan.call("seal_value", oid=oid_bin,
                            entry=("b", bytes(out)), nested=nested_bins)
            return ObjectRef(ObjectID(oid_bin))
        out = bytearray(size)
        write_framed(memoryview(out), meta, buffers)
        oid_bin = self._chan.call("put_val", data=bytes(out),
                                  nested=nested_bins)
        self.refs.adopt(oid_bin)
        return ObjectRef(ObjectID(oid_bin))

    def wait(self, refs, num_returns: int, timeout: Optional[float],
             fetch_local: bool = True):
        ids = [r.id for r in refs]
        ready_ids, pending_ids = self.store.wait(ids, num_returns, timeout)
        by_id = {r.id: r for r in refs}
        return ([by_id[i] for i in ready_ids],
                [by_id[i] for i in pending_ids])

    def release_stream_async(self, task_id: TaskID, from_index: int) -> None:
        # Called from generator __del__ (possibly inside a GC pause) —
        # never RPC here; the next flush (task end or 1 s sweep) sends it.
        self.refs.defer_stream_release(task_id.binary(), from_index)

    # -- tasks / actors ----------------------------------------------------

    def submit_task(self, fn, args, kwargs, options):
        from ray_tpu.util import tracing
        from ray_tpu.core.object_ref import collect_nested_refs

        # Ship the spec in wire form: top-level ObjectRef args become
        # location-agnostic WireRef("fetch") markers the EXECUTING
        # worker resolves through its own daemon, and the dependency
        # ids travel explicitly (parity: TaskSpec's dependency list).
        # This is what lets the host daemon dispatch the task locally
        # without unpickling anything (core/local_dispatch.py), and
        # the head park it on deps without live handles.
        def wire(v):
            if isinstance(v, ObjectRef):
                return WireRef("fetch", None, v.id.binary())
            return v

        top = [v.id.binary() for v in list(args) + list(kwargs.values())
               if isinstance(v, ObjectRef)]
        wargs = tuple(wire(a) for a in args)
        wkwargs = {k: wire(v) for k, v in kwargs.items()}
        with collect_nested_refs() as inner:
            spec = cloudpickle.dumps((fn, wargs, wkwargs))
        deps = list(dict.fromkeys(top))
        # Refs nested INSIDE container args are pinned by the owner but
        # are NOT scheduling dependencies (the task may never get()
        # them) — same top-level-only parking contract as the driver
        # path.
        pins = [b for b in dict.fromkeys(o.binary() for o in inner)
                if b not in set(deps)]
        rep = self._chan.call(
            "submit_task", spec=spec, options=options, deps=deps,
            pins=pins, trace_ctx=tracing.capture_context(),
        )
        if "stream" in rep:
            from ray_tpu.core.generator import ObjectRefGenerator

            return ObjectRefGenerator(TaskID(rep["stream"]))
        for b in rep["oids"]:
            self.refs.adopt(b)  # owner pre-registered our borrow
        return [ObjectRef(ObjectID(b)) for b in rep["oids"]]

    def create_actor(self, cls, args, kwargs, options):
        rep = self._chan.call(
            "create_actor", spec=cloudpickle.dumps((cls, args, kwargs)),
            options=options,
        )
        import types

        shell = types.SimpleNamespace(
            actor_id=ActorID(rep["actor_id"]),
            _creation_oid=ObjectID(rep["creation_oid"]),
        )
        return shell, ObjectRef(shell._creation_oid)

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args, kwargs, num_returns: Any = 1,
                          concurrency_group: Optional[str] = None):
        from ray_tpu.util import tracing

        rep = self._chan.call(
            "submit_actor_task", actor_id=actor_id.binary(),
            method=method_name, spec=cloudpickle.dumps((args, kwargs)),
            num_returns=num_returns, trace_ctx=tracing.capture_context(),
            cgroup=concurrency_group,
        )
        if "stream" in rep:
            from ray_tpu.core.generator import ObjectRefGenerator

            return ObjectRefGenerator(TaskID(rep["stream"]))
        for b in rep["oids"]:
            self.refs.adopt(b)
        return [ObjectRef(ObjectID(b)) for b in rep["oids"]]

    def cancel(self, oid: ObjectID, force: bool = False) -> None:
        self._chan.call("cancel_task", oid=oid.binary(), force=force)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._chan.call("kill_actor", actor_id=actor_id.binary(),
                        no_restart=no_restart)

    def ps_pull(self, channel: str, cursor: int = 0,
                timeout: float = 10.0):
        """Long-poll a head pubsub channel (core/pubsub.py) through
        the control plane; from a daemon's worker this forwards to the
        head like every other control op."""
        return tuple(self._chan.call(
            "ps_pull", rpc_timeout=timeout + 30.0,
            channel=channel, cursor=cursor, timeout=timeout))

    def get_named_actor(self, name: str) -> ActorID:
        return ActorID(self._chan.call("named_actor", name=name)
                       ["actor_id"])

    def named_actor_handle(self, name: str):
        rep = self._chan.call("named_actor", name=name)
        return (ActorID(rep["actor_id"]), rep["cls_name"], rep["table"],
                rep.get("cgroups") or {})

    # -- placement groups --------------------------------------------------

    def create_placement_group(self, bundles, strategy, name, lifetime):
        from ray_tpu.core.placement_group import PlacementGroup
        from ray_tpu.utils.ids import PlacementGroupID

        pg_id = self._chan.call(
            "create_pg", bundles=bundles, strategy=strategy, name=name,
            lifetime=lifetime,
        )
        return PlacementGroup(PlacementGroupID(pg_id), bundles, strategy,
                              name)

    def remove_placement_group(self, pg_id):
        self._chan.call("remove_pg", pg_id=pg_id.binary())

    def pg_ready_ref(self, pg_id):
        return ObjectRef(ObjectID(
            self._chan.call("pg_ready", pg_id=pg_id.binary())
        ))

    def get_named_placement_group(self, name: str):
        from ray_tpu.core.placement_group import PlacementGroup
        from ray_tpu.utils.ids import PlacementGroupID

        rep = self._chan.call("named_pg", name=name)
        return PlacementGroup(PlacementGroupID(rep["pg_id"]),
                              rep["bundles"], rep["strategy"],
                              rep["name"])

    def placement_group_table(self):
        return self._chan.call("pg_table")

    # -- cluster info ------------------------------------------------------

    def cluster_resources(self):
        return self._chan.call("cluster_resources")

    def available_resources(self):
        return self._chan.call("available_resources")

    def nodes(self):
        return self._chan.call("nodes")


# -- execution --------------------------------------------------------------


class _ActorExecutor:
    """Fixed thread pool that runs all of an actor's work, so a method
    sees the SAME thread across calls when max_concurrency == 1 —
    matching reference actor semantics (one scheduling-queue thread per
    actor; thread-locals like collective group contexts survive between
    method invocations)."""

    def __init__(self, n: int):
        import queue as _q

        self._q: "_q.Queue" = _q.Queue()
        for i in range(max(1, n)):
            threading.Thread(target=self._loop, daemon=True,
                             name=f"actor-exec-{i}").start()

    def _loop(self) -> None:
        while True:
            fn, box, ev = self._q.get()
            try:
                box.append(("ok", fn()))
            except BaseException as e:
                box.append(("err", e))
            ev.set()

    def run(self, fn):
        box: list = []
        ev = threading.Event()
        self._q.put((fn, box, ev))
        ev.wait()
        kind, val = box[0]
        if kind == "err":
            raise val
        return val


class _WorkerServer:
    def __init__(self):
        self._chan: Optional[MsgChannel] = None
        self._wr: Optional[WorkerRuntime] = None
        self._shm = None
        self._shm_threshold = 1 << 30
        self._actor_instance: Any = None
        self._actor_env = None
        self._actor_env_plugins = None
        self._actor_exec: Optional[_ActorExecutor] = None
        self._actor_group_execs: Dict[str, _ActorExecutor] = {}
        self._fn_cache: Dict[str, Any] = {}  # ship-once task functions
        # ALL plain tasks run on one persistent executor thread — the
        # reference's model (a worker's main loop executes tasks one at
        # a time), and load-bearing here: native extensions imported in
        # a transient thread can corrupt their TLS when that thread
        # exits (observed: pyarrow 25 segfaults on second use when first
        # imported in a short-lived thread).  A thread that never exits
        # sidesteps the entire class of bug.
        self._task_exec = _ActorExecutor(1)
        self._exit = threading.Event()
        # In-flight pushed work: the 1s ref sweep only flushes when
        # idle, so a sweep-sent del can't overtake a reply-attached add.
        self._busy = 0
        self._busy_lock = threading.Lock()
        # Cancellation registry: task_bin → ("thread", ident) while a
        # sync body runs, ("async", fut) while a coroutine is in flight
        # (parity: the executing-tasks map HandleCancelTask consults).
        self._running: Dict[bytes, Any] = {}
        self._running_lock = threading.Lock()
        # Shared event loop for async actor methods: concurrent calls
        # interleave their awaits on it instead of each getting a
        # private asyncio.run (parity: fiber.h async actors).
        self._loop = None

    # -- value encoding ----------------------------------------------------

    def _encode_result(self, value: Any, dest_oid: Optional[bytes]):
        """Wire entry for one produced value: written straight into the
        shared arena under its destination ObjectID when large, inline
        bytes otherwise.  Returns (entry, nested_oid_bins) — refs
        serialized inside the value, which the owner pins under the
        result oid (nested ownership)."""
        from ray_tpu.core.object_ref import collect_nested_refs

        with collect_nested_refs() as nested:
            meta, buffers = serialize_parts(value)
        nested_bins = [o.binary() for o in nested]
        size = framed_size(meta, buffers)
        if (self._shm is not None and dest_oid is not None
                and size >= self._shm_threshold):
            if try_shm_put(self._shm, dest_oid, meta, buffers, size):
                return ("shm", size), nested_bins
        out = bytearray(size)
        write_framed(memoryview(out), meta, buffers)
        return ("b", bytes(out)), nested_bins

    def _decode_args(self, args, kwargs) -> Tuple[tuple, dict]:
        def dec(v):
            if isinstance(v, WireRef):
                if v.kind in ("shm", "fetch"):
                    # "fetch": the bytes live on another node — the
                    # host daemon pulls them into the local arena on
                    # the get_raw fallback inside _read_shm.
                    return self._wr._read_shm(v.oid)
                return deserialize_object(v.data)
            return v

        return (tuple(dec(a) for a in args),
                {k: dec(v) for k, v in kwargs.items()})

    def _env_context(self, env, plugins_blob=None):
        if plugins_blob:
            from ray_tpu.runtime_env import register_plugin

            for plugin in cloudpickle.loads(plugins_blob).values():
                register_plugin(plugin)
        if env:
            from ray_tpu.runtime_env import materialize

            return materialize(env).applied()
        return contextlib.nullcontext()

    @staticmethod
    def _trace(ctx):
        from ray_tpu.util import tracing

        # The driver sends a context iff tracing is on over there —
        # mirror the flag so spans opened by user/library code in this
        # worker actually record (they ride the reply back via
        # drain_finished in _run_op).  A ctx-less call while enabled
        # means the driver turned tracing off; follow it down so the
        # is_enabled() fast path goes back to zero overhead.
        if ctx is not None:
            if not tracing.is_enabled():
                tracing.enable_tracing()
        elif tracing.is_enabled():
            tracing.disable_tracing()
        return tracing.activate(ctx)

    # -- request handling --------------------------------------------------

    def handle(self, chan: MsgChannel, msg: Dict[str, Any]) -> Any:
        op = msg["op"]
        if op == "task":
            return self._run_op(
                lambda: self._task_exec.run(lambda: self._run_task(msg)))
        if op == "actor_create":
            return self._run_op(lambda: self._actor_create(msg))
        if op == "actor_task":
            return self._run_op(lambda: self._actor_task(msg))
        if op == "cancel":
            return self._cancel(msg["task"])
        if op == "ping":
            return "pong"
        if op == "profile":
            # Blocking is fine: MsgChannel runs handlers on a pooled
            # thread per request, so tasks keep flowing during capture.
            return self._profile(msg)
        if op == "exit":
            self._exit.set()
            return None
        raise ValueError(f"unknown driver op {op!r}")

    @staticmethod
    def _profile(msg: Dict[str, Any]) -> List[str]:
        """One bounded jax.profiler capture in THIS worker (the fan-out
        target of the dashboard's POST /api/v0/profile).  Unavailable
        profiler → empty list, never an error reply."""
        from ray_tpu.util import xprof

        paths = xprof.capture(float(msg.get("duration_s", 1.0)),
                              msg.get("out_dir"))
        return paths or []

    def _cancel(self, task_bin: bytes) -> None:
        from ray_tpu.core.exceptions import TaskCancelledError
        from ray_tpu.utils.interrupt import async_raise

        with self._running_lock:
            entry = self._running.get(task_bin)
            if entry is None:
                return None  # already finished — no-op
            kind, target = entry
            if kind == "thread":
                # Under the lock: the executor thread unregisters (and
                # withdraws pending exceptions) under the same lock, so
                # this cannot hit a later task.
                async_raise(target, TaskCancelledError)
                return None
        target.cancel()  # asyncio future — thread-safe
        return None

    @contextlib.contextmanager
    def _cancellable(self, task_bin: bytes):
        """Register the calling thread as the executor of task_bin for
        the duration of the body."""
        from ray_tpu.utils.interrupt import clear_async_exc

        ident = threading.get_ident()
        if task_bin:
            with self._running_lock:
                self._running[task_bin] = ("thread", ident)
        try:
            yield
        finally:
            if task_bin:
                with self._running_lock:
                    self._running.pop(task_bin, None)
                    clear_async_exc(ident)

    def _run_op(self, body) -> Dict[str, Any]:
        """Run one pushed work item.  On success the pending borrow
        add/del batches ride IN the reply (the driver applies adds
        before pinning/sealing results and dels after); on failure they
        flush as a plain ref message — an error reply carries no values
        to pin, so ordering doesn't matter there."""
        with self._busy_lock:
            self._busy += 1
        try:
            try:
                rep = body()
            except BaseException:
                self._flush_refs()
                raise
            # Drain while still "busy" so the sweep can't grab (and
            # send out-of-band) a del that belongs after this reply.
            rep = rep if rep is not None else {}
            adds, dels = self._wr.refs.drain_batches()
            if adds:
                rep["ref_add"] = adds
            if dels:
                rep["ref_rem"] = dels
            from ray_tpu.util import tracing

            if tracing.is_enabled():
                # Spans finished in this worker ride the reply home;
                # concurrent calls may drain each other's spans, which
                # is fine — they all land in the same driver buffer.
                spans = tracing.drain_finished()
                if spans:
                    rep["spans"] = spans
            # Metric snapshots ride at most once per second per worker
            # (absolute cumulative state, so skipped replies lose
            # nothing — the next snapshot covers them).
            now = time.monotonic()
            if now - getattr(self, "_metrics_ship_t", 0.0) >= 1.0:
                from ray_tpu.util import metrics

                snap = metrics.snapshot_samples()
                if snap:
                    rep["metrics"] = snap
                    self._metrics_ship_t = now
            # Request-lifecycle rows (serve/request_events) federate
            # the same way — sys.modules guard: a worker that never
            # imported the serve stack must not load it for telemetry.
            reqev = sys.modules.get("ray_tpu.serve.request_events")
            if reqev is not None and \
                    now - getattr(self, "_reqev_ship_t", 0.0) >= 1.0:
                rows = reqev.snapshot_rows(local_only=True)
                if rows:
                    rep["request_events"] = rows
                    self._reqev_ship_t = now
            # Flight-recorder events ship incrementally (ship() moves a
            # cursor, so every event crosses exactly once); unlike the
            # absolute snapshots above there is no cadence gate — a
            # trigger event must reach the driver on the NEXT reply,
            # not up to a second later.
            frec = sys.modules.get("ray_tpu.util.flight_recorder")
            if frec is not None:
                evs = frec.ship()
                if evs:
                    rep["flightrec"] = evs
            # Time-series points ship cursor-style too (util/timeseries
            # drains its outbox, so every point crosses exactly once);
            # the worker's 1 Hz sampler bounds the payload to roughly
            # one tick's points per reply.
            tser = sys.modules.get("ray_tpu.util.timeseries")
            if tser is not None:
                pts = tser.ship()
                if pts:
                    rep["timeseries"] = pts
            return rep
        finally:
            with self._busy_lock:
                self._busy -= 1

    def _flush_refs(self) -> None:
        if self._wr is not None:
            self._wr.refs.flush()

    def _run_task(self, msg: Dict[str, Any]) -> Any:
        fhash = msg.get("fn_hash")
        if fhash is not None:
            # Ship-once function protocol: the blob rides the first
            # call only (parity: function-manager export by hash).
            fn = self._fn_cache.get(fhash)
            if fn is None:
                blob = msg.get("fn_blob")
                if blob is None:
                    raise RuntimeError(
                        f"unknown function hash {fhash} (no blob shipped)")
                fn = cloudpickle.loads(blob)
                self._fn_cache[fhash] = fn
            args, kwargs = cloudpickle.loads(msg["spec"])
        else:
            fn, args, kwargs = cloudpickle.loads(msg["spec"])
        args, kwargs = self._decode_args(args, kwargs)
        with self._env_context(msg.get("env"), msg.get("env_plugins")), \
                self._trace(msg.get("trace_ctx")), \
                self._cancellable(msg.get("task") or b""):
            result = fn(*args, **kwargs)
            if msg.get("streaming"):
                self._stream(result, TaskID(msg["task"]), msg["name"])
                return {"streamed": True}
        return self._encode_reply(result, msg)

    def _ensure_loop(self):
        with self._running_lock:
            if self._loop is None:
                import asyncio

                self._loop = asyncio.new_event_loop()
                threading.Thread(
                    target=self._loop.run_forever, daemon=True,
                    name="async-actor-loop",
                ).start()
            return self._loop

    def _run_coroutine(self, coro, task_bin: bytes):
        """Run an async actor method on the shared loop so concurrent
        calls interleave their awaits; cancellable via the registry."""
        import asyncio
        import concurrent.futures as _cf

        from ray_tpu.core.exceptions import TaskCancelledError

        loop = self._ensure_loop()
        fut = asyncio.run_coroutine_threadsafe(coro, loop)
        if task_bin:
            with self._running_lock:
                self._running[task_bin] = ("async", fut)
        try:
            return fut.result()
        except (_cf.CancelledError, asyncio.CancelledError):
            raise TaskCancelledError(
                TaskID(task_bin).hex() if task_bin else "")
        finally:
            if task_bin:
                with self._running_lock:
                    self._running.pop(task_bin, None)

    def _encode_reply(self, result, msg: Dict[str, Any]) -> Dict[str, Any]:
        num_returns = msg.get("num_returns", 1)
        returns = msg.get("returns", [])
        if num_returns == 1:
            entry, nested = self._encode_result(
                result, returns[0] if returns else None)
            return {"results": [entry], "nested": [nested]}
        values = list(result)
        if len(values) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{len(values)} values"
            )
        entries, nesteds = [], []
        for i, v in enumerate(values):
            entry, nested = self._encode_result(
                v, returns[i] if i < len(returns) else None)
            entries.append(entry)
            nesteds.append(nested)
        return {"results": entries, "nested": nesteds}

    def _stream(self, result, task_id: TaskID, name: str) -> None:
        """Seal yielded items into the driver's store one by one
        (parity: the streaming-generator executor, _raylet.pyx:918)."""
        from ray_tpu.core.exceptions import TaskError
        from ray_tpu.core.generator import EndOfStream

        i = 0
        try:
            if not hasattr(result, "__iter__"):
                raise TypeError(
                    f"streaming task {name!r} must return an iterable, "
                    f"got {type(result).__name__}"
                )
            for item in result:
                oid = ObjectID.for_task_return(task_id, i)
                entry, nested = self._encode_result(item, oid.binary())
                self._chan.call("seal_value", oid=oid.binary(), entry=entry,
                                nested=nested)
                i += 1
        except BaseException as e:
            err = e if isinstance(e, TaskError) else TaskError(name, e)
            self._chan.call(
                "seal_error",
                oid=ObjectID.for_task_return(task_id, i).binary(),
                error=err, if_pending=False,
            )
            raise
        self._chan.call(
            "seal_error", oid=ObjectID.for_task_return(task_id, i).binary(),
            error=EndOfStream(), if_pending=False,
        )

    def _actor_create(self, msg: Dict[str, Any]) -> None:
        cls, args, kwargs = cloudpickle.loads(msg["spec"])
        args, kwargs = self._decode_args(args, kwargs)
        self._actor_env = msg.get("env")
        self._actor_env_plugins = msg.get("env_plugins")
        self._actor_exec = _ActorExecutor(msg.get("max_concurrency", 1))
        # One executor pool per named concurrency group (parity:
        # concurrency_group_manager.cc — per-group BoundedExecutor), so
        # a stalled group cannot serialize another group's calls.
        self._actor_group_execs = {
            g: _ActorExecutor(max(1, int(n)))
            for g, n in (msg.get("concurrency_groups") or {}).items()
        }

        def construct():
            with self._env_context(self._actor_env,
                                   self._actor_env_plugins):
                self._actor_instance = cls(*args, **kwargs)

        # __init__ runs on the executor thread too, so instance state
        # bound to the thread (thread-locals, event loops) carries over
        # into method calls.
        self._actor_exec.run(construct)
        return None

    def _actor_task(self, msg: Dict[str, Any]) -> Any:
        if self._actor_instance is None:
            raise RuntimeError("no actor constructed in this worker")
        import inspect as _inspect

        method = getattr(self._actor_instance, msg["method"], None)
        if _inspect.iscoroutinefunction(method):
            # Async methods bypass the executor: each request's handler
            # thread parks on the coroutine's future while the SHARED
            # loop interleaves all of them (parity: fiber.h async
            # actors) — routing through the 1-thread executor would
            # serialize exactly what async actors exist to overlap.
            # (The driver-side shell bounds per-group async concurrency.)
            return self._actor_task_body(msg)
        cgroup = msg.get("cgroup")
        exec_ = (getattr(self, "_actor_group_execs", {}).get(cgroup)
                 if cgroup else None) or self._actor_exec
        return exec_.run(lambda: self._actor_task_body(msg))

    def _actor_task_body(self, msg: Dict[str, Any]) -> Any:
        args, kwargs = cloudpickle.loads(msg["spec"])
        args, kwargs = self._decode_args(args, kwargs)
        method = getattr(self._actor_instance, msg["method"])
        task_bin = msg.get("task") or b""
        with self._env_context(self._actor_env, self._actor_env_plugins), \
                self._trace(msg.get("trace_ctx")):
            import inspect as _inspect

            if _inspect.iscoroutinefunction(method):
                # Shared loop: concurrent calls interleave their awaits
                # (each handler thread blocks, the coroutines don't).
                result = self._run_coroutine(method(*args, **kwargs),
                                             task_bin)
            else:
                with self._cancellable(task_bin):
                    result = method(*args, **kwargs)
                if _inspect.iscoroutine(result):
                    result = self._run_coroutine(result, task_bin)
            if msg.get("num_returns") == "streaming":
                self._stream(result, TaskID(msg["task"]), msg["method"])
                return {"streamed": True}
        return self._encode_reply(result, msg)

    # -- direct transport --------------------------------------------------

    def _direct_accept_loop(self, cluster_token: str) -> None:
        from ray_tpu.util.client.common import server_handshake

        while not self._exit.is_set():
            try:
                conn, peer = self._direct_listener.accept()
            except OSError:
                return

            def serve(conn=conn, peer=peer):
                conn.settimeout(10.0)
                if not server_handshake(conn, cluster_token or None):
                    conn.close()
                    return
                conn.settimeout(None)
                MsgChannel(conn, self._handle_direct,
                           name=f"direct-{peer[0]}").start()

            threading.Thread(target=serve, daemon=True,
                             name="direct-serve").start()

    def _handle_direct(self, chan: MsgChannel, msg: Dict[str, Any]) -> Any:
        """Ops pushed over a direct owner channel.  Results sealed into
        the local arena must ALSO be indexed at this node's daemon (the
        proxy path did that from the reply; direct replies bypass it).
        The index update is SYNCHRONOUS, before the owner sees the
        reply: the owner may immediately direct another node to pull
        from this daemon, and the daemon's spill-ahead-of-eviction
        policy needs to see arena pressure as it builds, not after."""
        rep = self.handle(chan, msg)
        if isinstance(rep, dict) and rep.get("results"):
            for oid_bin, (kind, payload) in zip(msg.get("returns") or (),
                                                rep["results"]):
                if kind == "shm":
                    try:
                        self._chan.call("mark_shm_local", oid=oid_bin,
                                        size=payload)
                    except Exception:
                        pass  # daemon gone: node death owns cleanup
        return rep

    # -- bootstrap ---------------------------------------------------------

    def main(self) -> int:
        import faulthandler

        faulthandler.enable()  # crashing workers leave a stack trace
        sock_path = os.environ.get("RAYTPU_WORKER_SOCKET")
        token = os.environ.get("RAYTPU_WORKER_TOKEN", "")
        if not sock_path:
            print("RAYTPU_WORKER_SOCKET not set", file=sys.stderr)
            return 2
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(sock_path)
        from ray_tpu.util.client.common import (
            exchange_versions,
            recv_msg,
            send_msg,
        )

        exchange_versions(sock)

        # Direct task transport (parity: the owner pushing tasks to a
        # leased worker over its own gRPC channel rather than through
        # the raylet, direct_task_transport.cc → PushTask): a TCP
        # listener remote owners dial directly, skipping the daemon's
        # per-task forwarding.  Token-gated beyond loopback (same trust
        # rule as the peer/object plane).
        cluster_token = os.environ.get("RAYTPU_CLUSTER_TOKEN", "")
        self._direct_listener = socket.socket(socket.AF_INET,
                                              socket.SOCK_STREAM)
        self._direct_listener.setsockopt(socket.SOL_SOCKET,
                                         socket.SO_REUSEADDR, 1)
        self._direct_listener.bind(
            ("0.0.0.0" if cluster_token else "127.0.0.1", 0))
        self._direct_listener.listen(16)
        wport = self._direct_listener.getsockname()[1]
        # NOTE: the accept loop starts only after _wr exists — a direct
        # push must never race runtime construction.

        send_msg(sock, {"kind": "req", "mid": 0, "op": "hello",
                        "token": token, "pid": os.getpid(),
                        "wport": wport})
        welcome = recv_msg(sock)
        if not welcome.get("ok"):
            return 3
        info = welcome["value"]
        from ray_tpu.utils.config import get_config

        try:
            get_config().update(info.get("config") or {})
        except Exception:
            pass
        for p in info.get("sys_path") or []:
            if p not in sys.path:
                sys.path.append(p)
        try:
            if info.get("cwd"):
                os.chdir(info["cwd"])
        except OSError:
            pass
        self._shm_threshold = info.get("shm_threshold", 1 << 30)
        if info.get("shm_name"):
            try:
                from ray_tpu.core.shm_store import SharedMemoryStore

                self._shm = SharedMemoryStore.connect(info["shm_name"])
            except Exception as e:
                # Degraded but functional: large values travel as bytes
                # through the driver (see _read_shm / get_raw no_shm).
                print(f"[ray_tpu worker {os.getpid()}] shared-memory "
                      f"attach failed ({e!r}); falling back to inline "
                      f"transfers", file=sys.stderr)
                self._shm = None
        self._chan = MsgChannel(sock, self.handle, name="driver",
                                on_close=lambda: self._exit.set())
        self._wr = WorkerRuntime(self._chan, self._shm,
                                 self._shm_threshold)
        # Install the proxy as THE runtime for this process: any
        # ray_tpu API call in user code now routes to the driver.
        from ray_tpu.core import api

        api._runtime = self._wr
        # Always-on telemetry history: sample this process's metric
        # registry into bounded rings; points ride task replies home
        # (see _run_op's timeseries ship).
        try:
            from ray_tpu.util import timeseries

            timeseries.ensure_started()
        except Exception:
            pass
        threading.Thread(target=self._direct_accept_loop,
                         args=(cluster_token,), daemon=True,
                         name="direct-accept").start()

        def ref_sweep():
            # Handles dropped by long-lived actor state between tasks
            # (reply-attached batches cover everything else).  Only
            # when idle: a sweep del racing an in-flight reply's adds
            # would leak the borrow.
            while not self._exit.wait(1.0):
                with self._busy_lock:
                    busy = self._busy
                if busy:
                    continue
                try:
                    self._wr.refs.flush()
                except Exception:
                    pass

        threading.Thread(target=ref_sweep, name="ref-sweep",
                         daemon=True).start()
        self._chan.start()
        self._exit.wait()
        # Let in-flight replies flush before dying.
        self._chan.close()
        return 0


def main() -> int:
    return _WorkerServer().main()


if __name__ == "__main__":
    sys.exit(main())
