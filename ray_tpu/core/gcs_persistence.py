"""Control-plane persistence — the Redis-backed GCS storage equivalent.

Parity with the reference's pluggable GCS store (ray:
src/ray/gcs/store_client/store_client.h — the StoreClient interface;
src/ray/gcs/store_client/redis_store_client.h:33 the external backend
behind GcsTableStorage; selection at gcs_server.cc:517-518): the
control plane's durable tables (KV, detached-actor creation specs,
placement-group specs) snapshot through a :class:`StoreClient`.

Backends:

* :class:`FileStore` — atomic local snapshot (tmp + rename); a crash
  loses at most one flush period of writes — Redis "appendfsync
  everysec" semantics.  Survives head PROCESS loss.
* :class:`MirroredStore` — a primary plus replica stores, written
  best-effort on every flush.  With a replica on another failure
  domain (a peer machine's export, an NFS/GCS-bucket mount), the
  control plane survives head MACHINE loss: bootstrap loads the
  NEWEST readable snapshot across primary + mirrors, so a head
  restarted on a fresh machine with only the mirror reachable
  recovers its tables (the Redis deployment's role, without requiring
  a Redis in the image).

A driver/head restart pointed at the same store rebuilds the tables
(gcs_init_data.cc replays tables the same way).
"""

from __future__ import annotations

import os
import pickle
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence

_FORMAT_VERSION = 2


class StoreClient:
    """Minimal durable-snapshot interface (parity:
    src/ray/gcs/store_client/store_client.h, narrowed to the snapshot
    granularity this control plane persists at)."""

    def load_blob(self) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def save_blob(self, blob: Dict[str, Any]) -> None:
        raise NotImplementedError

    def describe(self) -> str:
        raise NotImplementedError


class FileStore(StoreClient):
    """Atomic snapshot file (tmp + fsync + rename)."""

    def __init__(self, path: str):
        self.path = path

    def load_blob(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path, "rb") as f:
                blob = pickle.load(f)
        except Exception:
            # OSError, UnpicklingError, but also AttributeError/
            # ImportError/ValueError from foreign or corrupt pickles —
            # any unreadable snapshot means "no data here", never
            # "fail init" (recovery is the whole point).
            return None
        if not isinstance(blob, dict):
            return None
        if blob.get("version") == 1 and "tables" in blob:
            # v1 (pre-mirror) snapshots carry no seq/saved_at: migrate
            # in place rather than silently dropping a cluster's
            # persisted control plane on upgrade.
            return {"version": _FORMAT_VERSION, "seq": 0,
                    "saved_at": 0.0, "tables": blob["tables"]}
        if blob.get("version") != _FORMAT_VERSION:
            return None
        return blob

    def save_blob(self, blob: Dict[str, Any]) -> None:
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".gcs-snap-")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(blob, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def describe(self) -> str:
        return f"file:{self.path}"


class MirroredStore(StoreClient):
    """Primary + best-effort replicas; loads pick the NEWEST readable
    snapshot (each blob carries a monotonic save counter + wall time),
    so bootstrap works from whichever copy survived."""

    def __init__(self, primary: StoreClient,
                 mirrors: Sequence[StoreClient]):
        self.primary = primary
        self.mirrors = list(mirrors)
        self._warned: set = set()

    def load_blob(self) -> Optional[Dict[str, Any]]:
        candidates = []
        for store in [self.primary] + self.mirrors:
            blob = store.load_blob()
            if blob is not None:
                candidates.append(blob)
        if not candidates:
            return None
        # Seq dominates, wall time breaks ties: the save counter is
        # resumed from the restored blob on restart, so it is monotonic
        # across head generations — unlike saved_at, which a replacement
        # head with a skewed (or stepped-back) clock can stamp EARLIER
        # than a genuinely stale copy, silently restoring a dead
        # generation that resurrects deleted actors and drops recent
        # writes.  saved_at only arbitrates between copies of the same
        # seq (e.g. a mirror that got the write and a primary that got
        # re-written after a partial failure).
        return max(candidates,
                   key=lambda b: (b.get("seq", 0), b.get("saved_at", 0)))

    def _warn_once(self, store: StoreClient, err: Exception,
                   role: str) -> None:
        key = store.describe()
        if key not in self._warned:
            self._warned.add(key)
            import logging

            logging.getLogger("ray_tpu.gcs").warning(
                "GCS %s store %s is failing (%r) — snapshot "
                "durability is degraded until it recovers", role, key,
                err)

    def save_blob(self, blob: Dict[str, Any]) -> None:
        # Every store is written INDEPENDENTLY — a dead primary (the
        # exact head-disk failure mirroring exists for) must not stop
        # the replicas from advancing.  Each failing store WARNS once;
        # the save as a whole fails only when NO copy persisted.
        first_err: Optional[Exception] = None
        ok = 0
        for role, store in [("primary", self.primary)] + [
                ("mirror", m) for m in self.mirrors]:
            try:
                store.save_blob(blob)
                ok += 1
                self._warned.discard(store.describe())
            except Exception as e:
                if first_err is None:
                    first_err = e
                self._warn_once(store, e, role)
        if ok == 0 and first_err is not None:
            raise first_err

    def describe(self) -> str:
        return " + ".join(s.describe()
                          for s in [self.primary] + self.mirrors)


class KvStoreClient(StoreClient):
    """Snapshot blob stored as one pickled value in the cluster KV.

    The runtime KV lives on the driver's runtime instance, so it
    survives any ACTOR's death (the serve controller checkpoints through
    this), and it is itself disk-persisted by :class:`GcsPersistence`
    when ``gcs_persist_path`` is configured — a checkpoint written here
    inherits whatever durability tier the cluster's GCS storage has.
    Unlike :class:`FileStore`, a present-but-unreadable blob is reported
    loudly: the value existed, so silence would hide corruption.
    """

    def __init__(self, kv, namespace: str = "serve",
                 key: bytes = b"controller::checkpoint"):
        self._kv = kv
        self.namespace = namespace
        self.key = key if isinstance(key, bytes) else key.encode()

    def _warn(self, why: str) -> None:
        import logging

        logging.getLogger("ray_tpu.gcs").warning(
            "GCS store %s holds an unreadable snapshot (%s) — treating "
            "it as absent", self.describe(), why)

    def load_blob(self) -> Optional[Dict[str, Any]]:
        raw = self._kv.get(self.key, namespace=self.namespace)
        if raw is None:
            return None
        try:
            blob = pickle.loads(raw)
        except Exception as e:
            self._warn(f"corrupt pickle: {e!r}")
            return None
        if not isinstance(blob, dict):
            self._warn(f"not a snapshot dict: {type(blob).__name__}")
            return None
        if blob.get("version") != _FORMAT_VERSION:
            self._warn(f"format version {blob.get('version')!r} != "
                       f"{_FORMAT_VERSION}")
            return None
        return blob

    def save_blob(self, blob: Dict[str, Any]) -> None:
        self._kv.put(self.key, pickle.dumps(blob),
                     namespace=self.namespace)

    def describe(self) -> str:
        return f"kv:{self.namespace}/{self.key.decode(errors='replace')}"


def make_store(path: str, mirror_paths: Sequence[str] = ()) -> StoreClient:
    """Store from config strings (parity: gcs_server.cc:517-518
    choosing the storage backend from flags)."""
    primary = FileStore(path)
    mirrors = [FileStore(p) for p in mirror_paths if p]
    if mirrors:
        return MirroredStore(primary, mirrors)
    return primary


class GcsPersistence:
    """Snapshot + dirty-flag flusher thread over a StoreClient."""

    def __init__(self, path: str, flush_period_s: float = 0.2,
                 mirror_paths: Sequence[str] = (),
                 store: Optional[StoreClient] = None):
        # An explicit store (e.g. KvStoreClient, or a MirroredStore over
        # one) bypasses path-based construction — the serve controller's
        # checkpointer reuses this flusher over the cluster KV.
        self.store = store if store is not None \
            else make_store(path, mirror_paths)
        self.path = path
        self._period = flush_period_s
        self._dirty = threading.Event()
        self._stop = threading.Event()
        # Serializes saves: the final flush must never lose to a stale
        # in-flight periodic save's os.replace.
        self._save_lock = threading.Lock()
        self._seq = 0
        self._collect: Optional[Callable[[], Dict[str, Any]]] = None
        self._thread: Optional[threading.Thread] = None

    # -- load --------------------------------------------------------------

    def load(self) -> Optional[Dict[str, Any]]:
        """The newest readable snapshot's tables, or None."""
        blob = self.store.load_blob()
        if blob is None:
            return None
        # Resume the save counter past the restored snapshot so a
        # restart's snapshots outrank the old generation on mirrors.
        self._seq = int(blob.get("seq", 0))
        return blob.get("tables")

    # -- save --------------------------------------------------------------

    def save(self, tables: Dict[str, Any]) -> None:
        self._seq += 1
        self.store.save_blob({
            "version": _FORMAT_VERSION,
            "seq": self._seq,
            "saved_at": time.time(),
            "tables": tables,
        })

    # -- flusher -----------------------------------------------------------

    def start_flusher(self, collect: Callable[[], Dict[str, Any]]) -> None:
        self._collect = collect
        self._thread = threading.Thread(
            target=self._flush_loop, name="gcs-flush", daemon=True
        )
        self._thread.start()

    def mark_dirty(self) -> None:
        self._dirty.set()

    def _flush_loop(self) -> None:
        while not self._stop.wait(self._period):
            if self._dirty.is_set():
                self._dirty.clear()
                self._try_flush()

    def _try_flush(self) -> None:
        try:
            with self._save_lock:
                self.save(self._collect())
        except Exception:
            pass  # persistence is best-effort; next tick retries

    def close(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            # Join BEFORE the final flush: an in-flight periodic save
            # could otherwise rename its stale snapshot over the final
            # one and silently lose the last writes.  If it is stuck
            # (hung filesystem), the save lock still orders us after it
            # — bounded, so a truly hung fsync can't wedge shutdown.
            self._thread.join(timeout=5.0)
        if final_flush and self._collect is not None:
            if self._save_lock.acquire(timeout=10.0):
                try:
                    self.save(self._collect())
                except Exception:
                    pass
                finally:
                    self._save_lock.release()
