"""Control-plane persistence — the Redis-backed GCS storage equivalent.

Parity with the reference's pluggable GCS store (ray:
src/ray/gcs/store_client/redis_store_client.h:33 behind GcsTableStorage,
selection at gcs_server.cc:517-518): the control plane's durable tables
(KV, detached-actor creation specs, placement-group specs) snapshot to a
file; a driver restart pointed at the same path rebuilds them
(gcs_init_data.cc replays tables the same way).  Snapshots are atomic
(tmp + rename); a crash loses at most one flush period of writes —
Redis "appendfsync everysec" semantics.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import threading
from typing import Any, Callable, Dict, Optional

_FORMAT_VERSION = 1


class GcsPersistence:
    """Atomic snapshot file + dirty-flag flusher thread."""

    def __init__(self, path: str, flush_period_s: float = 0.2):
        self.path = path
        self._period = flush_period_s
        self._dirty = threading.Event()
        self._stop = threading.Event()
        # Serializes saves: the final flush must never lose to a stale
        # in-flight periodic save's os.replace.
        self._save_lock = threading.Lock()
        self._collect: Optional[Callable[[], Dict[str, Any]]] = None
        self._thread: Optional[threading.Thread] = None

    # -- load --------------------------------------------------------------

    def load(self) -> Optional[Dict[str, Any]]:
        """The last snapshot, or None (missing/corrupt file — a torn
        write can't happen thanks to rename, but a foreign file can)."""
        try:
            with open(self.path, "rb") as f:
                blob = pickle.load(f)
        except Exception:
            # OSError, UnpicklingError, but also AttributeError/
            # ImportError/ValueError from foreign or corrupt pickles —
            # any unreadable snapshot means "start fresh", never "fail
            # init" (recovery is the whole point of this file).
            return None
        if (not isinstance(blob, dict)
                or blob.get("version") != _FORMAT_VERSION):
            return None
        return blob.get("tables")

    # -- save --------------------------------------------------------------

    def save(self, tables: Dict[str, Any]) -> None:
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".gcs-snap-")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump({"version": _FORMAT_VERSION, "tables": tables}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- flusher -----------------------------------------------------------

    def start_flusher(self, collect: Callable[[], Dict[str, Any]]) -> None:
        self._collect = collect
        self._thread = threading.Thread(
            target=self._flush_loop, name="gcs-flush", daemon=True
        )
        self._thread.start()

    def mark_dirty(self) -> None:
        self._dirty.set()

    def _flush_loop(self) -> None:
        while not self._stop.wait(self._period):
            if self._dirty.is_set():
                self._dirty.clear()
                self._try_flush()

    def _try_flush(self) -> None:
        try:
            with self._save_lock:
                self.save(self._collect())
        except Exception:
            pass  # persistence is best-effort; next tick retries

    def close(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            # Join BEFORE the final flush: an in-flight periodic save
            # could otherwise rename its stale snapshot over the final
            # one and silently lose the last writes.  If it is stuck
            # (hung filesystem), the save lock still orders us after it
            # — bounded, so a truly hung fsync can't wedge shutdown.
            self._thread.join(timeout=5.0)
        if final_flush and self._collect is not None:
            if self._save_lock.acquire(timeout=10.0):
                try:
                    self.save(self._collect())
                except Exception:
                    pass
                finally:
                    self._save_lock.release()
