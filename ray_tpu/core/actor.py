"""Actor API: @remote classes, handles, methods.

Parity with the reference (ray: python/ray/actor.py — ActorClass:384,
ActorMethod:98, ActorHandle:1025): ``Cls.remote(...)`` creates the
actor, ``handle.method.remote(...)`` submits ordered tasks,
``handle.options(name=...)``/`get_if_exists` for named actors.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.runtime import ActorOptions
from ray_tpu.utils.ids import ActorID

_VALID_ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "name", "get_if_exists",
    "max_restarts", "max_concurrency", "concurrency_groups",
    "execute_out_of_order", "lifetime", "scheduling_strategy",
    "placement_group", "placement_bundle_index", "runtime_env",
}

_METHOD_OPTION_ATTR = "__raytpu_method_options__"


def method(**options):
    """Decorator for per-method defaults, e.g. @method(num_returns=2)
    (parity: ray.method)."""

    def wrap(fn):
        setattr(fn, _METHOD_OPTION_ATTR, options)
        return fn

    return wrap


def _collect_method_option(cls: type, key: str) -> Dict[str, Any]:
    """name → value table of one @method(...) option across a class."""
    table: Dict[str, Any] = {}
    for name in dir(cls):
        fn = getattr(cls, name, None)
        opts = getattr(fn, _METHOD_OPTION_ATTR, None)
        if opts and key in opts:
            table[name] = opts[key]
    return table


def collect_method_num_returns(cls: type) -> Dict[str, int]:
    """@method(num_returns=...) table for a class — shared by direct
    handles and handles recovered via get_actor."""
    return _collect_method_option(cls, "num_returns")


def collect_method_cgroups(cls: type) -> Dict[str, str]:
    """@method(concurrency_group=...) routing table (parity: ray's
    decorated concurrency-group assignment, python/ray/actor.py)."""
    return _collect_method_option(cls, "concurrency_group")


def _make_actor_options(defaults: Dict[str, Any], overrides: Dict[str, Any]
                        ) -> ActorOptions:
    merged = {**defaults, **overrides}
    bad = set(merged) - _VALID_ACTOR_OPTIONS
    if bad:
        raise ValueError(
            f"invalid actor option(s) {sorted(bad)}; valid: "
            f"{sorted(_VALID_ACTOR_OPTIONS)}"
        )
    return ActorOptions(**merged)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._cgroup = concurrency_group

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        from ray_tpu.core import api

        refs = api.runtime().submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=self._num_returns,
            concurrency_group=self._cgroup,
        )
        if self._num_returns == "streaming":
            return refs  # an ObjectRefGenerator
        return refs[0] if self._num_returns == 1 else refs

    def options(self, *, num_returns: Optional[int] = None,
                concurrency_group: Optional[str] = None) -> "ActorMethod":
        return ActorMethod(self._handle, self._name,
                           num_returns or self._num_returns,
                           concurrency_group or self._cgroup)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._name!r} cannot be called directly — use "
            f".{self._name}.remote(...)"
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, cls_name: str,
                 method_num_returns: Optional[Dict[str, int]] = None,
                 creation_ref: Optional[ObjectRef] = None,
                 method_cgroups: Optional[Dict[str, str]] = None):
        object.__setattr__(self, "_actor_id", actor_id)
        object.__setattr__(self, "_cls_name", cls_name)
        object.__setattr__(self, "_method_num_returns", method_num_returns or {})
        object.__setattr__(self, "_creation_ref", creation_ref)
        object.__setattr__(self, "_method_cgroups", method_cgroups or {})

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(
            self, name, self._method_num_returns.get(name, 1),
            self._method_cgroups.get(name),
        )

    def __repr__(self):
        return f"ActorHandle({self._cls_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._cls_name, self._method_num_returns, None,
             self._method_cgroups),
        )


class ActorClass:
    def __init__(self, cls: type, **default_options):
        self._cls = cls
        self._default_options = default_options
        self._method_num_returns = collect_method_num_returns(cls)
        self._method_cgroups = collect_method_cgroups(cls)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__!r} cannot be instantiated "
            f"directly — use {self._cls.__name__}.remote(...)"
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._create(args, kwargs, {})

    def bind(self, *args, **kwargs):
        """Lazy DAG node (parity: ray DAGNode bind, dag/class_node.py)."""
        from ray_tpu.util.dag import bind_class

        return bind_class(self, *args, **kwargs)

    def options(self, **overrides) -> "_BoundActorOptions":
        _make_actor_options(self._default_options, overrides)  # validate
        return _BoundActorOptions(self, overrides)

    def _create(self, args, kwargs, overrides) -> ActorHandle:
        from ray_tpu.core import api

        opts = _make_actor_options(self._default_options, overrides)
        shell, creation_ref = api.runtime().create_actor(
            self._cls, args, kwargs, opts
        )
        return ActorHandle(
            shell.actor_id, self._cls.__name__, self._method_num_returns,
            creation_ref, self._method_cgroups,
        )

    @property
    def underlying(self) -> type:
        return self._cls


class _BoundActorOptions:
    def __init__(self, ac: ActorClass, overrides: Dict[str, Any]):
        self._ac = ac
        self._overrides = overrides

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._ac._create(args, kwargs, self._overrides)
