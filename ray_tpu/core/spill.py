"""External storage for spilled objects.

Parity with the reference's object-spilling IO layer
(ray: python/ray/_private/external_storage.py — FileSystemStorage :246,
spill/restore URL scheme, fused multi-object spill files with
``?offset=..&size=..`` addressing; driven by the raylet's
LocalObjectManager, src/ray/raylet/local_object_manager.h:41).

Objects are spilled in fused batches: many small objects land in one
file (parity: ``min_spilling_size`` fusion, external_storage.py
``spill_objects`` writing url_with_offset) so restore is one seek+read.
"""

from __future__ import annotations

import os
import threading
import urllib.parse
from typing import Dict, List, Sequence, Tuple


class FileSystemStorage:
    """Spill directory on local disk (parity: FileSystemStorage,
    external_storage.py:246)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._seq = 0
        self._lock = threading.Lock()
        # fused-file path → dead (offset, size) segments; the file is
        # unlinked when the whole byte range is dead.
        self._dead_segments: Dict[str, List[Tuple[int, int]]] = {}

    def _next_path(self) -> str:
        with self._lock:
            self._seq += 1
            return os.path.join(self.directory, f"spill-{self._seq:08d}.bin")

    def spill_objects(self, objects: Sequence[Tuple[bytes, bytes]]
                      ) -> List[str]:
        """Write a fused file of (key, payload) pairs; returns one
        ``file://path?offset=o&size=n`` URI per object, in order."""
        if not objects:
            return []
        path = self._next_path()
        uris: List[str] = []
        offset = 0
        with open(path, "wb") as f:
            for _key, payload in objects:
                f.write(payload)
                uris.append(
                    f"file://{path}?offset={offset}&size={len(payload)}"
                )
                offset += len(payload)
        return uris

    @staticmethod
    def _parse(uri: str) -> Tuple[str, int, int]:
        parsed = urllib.parse.urlparse(uri)
        if parsed.scheme != "file":
            raise ValueError(f"unsupported spill URI scheme: {uri}")
        qs = urllib.parse.parse_qs(parsed.query)
        return parsed.path, int(qs["offset"][0]), int(qs["size"][0])

    def restore(self, uri: str) -> bytes:
        path, offset, size = self._parse(uri)
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(size)
        if len(data) != size:
            raise IOError(f"short read restoring {uri}: "
                          f"{len(data)} != {size}")
        return data

    def delete(self, uris: Sequence[str]) -> None:
        """Delete spilled data.  A fused file is removed only once every
        object inside it has been deleted (parity: external_storage
        tracks fused-file liveness via the url_with_offset refs)."""
        by_file: Dict[str, List[Tuple[int, int]]] = {}
        for uri in uris:
            path, offset, size = self._parse(uri)
            by_file.setdefault(path, []).append((offset, size))
        with self._lock:
            for path, segments in by_file.items():
                dead = self._dead_segments.setdefault(path, [])
                dead.extend(segments)
                try:
                    file_size = os.path.getsize(path)
                except OSError:
                    self._dead_segments.pop(path, None)
                    continue
                if sum(s for _, s in dead) >= file_size:
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    self._dead_segments.pop(path, None)
