"""In-process object store (local runtime backend).

Semantics parity with the reference's two-tier store — the in-process
memory store for small/inlined values (ray:
src/ray/core_worker/store_provider/memory_store/memory_store.h:43) and
plasma for large ones (plasma/store.h:55): objects are immutable,
created-then-sealed, readable by many, and survive until released.

This Python implementation is the single-process backend; the C++
shared-memory store (ray_tpu/_native) plugs in behind the same
interface for the multi-process runtime.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ray_tpu.core.exceptions import (
    GetTimeoutError,
    ObjectFreedError,
    ObjectLostError,
)
from ray_tpu.core.object_ref import ObjectState, collect_nested_refs
from ray_tpu.utils.ids import ObjectID
import itertools as _itertools

from ray_tpu.utils.serialization import (
    deserialize_object,
    framed_size,
    serialize_parts,
    try_shm_put,
    write_framed,
)

_shm_seq = _itertools.count()


class LocalObjectStore:
    """Thread-safe map ObjectID → sealed value (serialized or in-band).

    Values whose serialized form exceeds ``shm_threshold`` bytes are
    promoted into the C++ shared-memory store (ray_tpu.core.shm_store) —
    the plasma-equivalent tier: zero-copy reads, LRU eviction, visible
    to other processes that attach to the segment.
    """

    def __init__(self, *, serialize_always: bool = True,
                 shm_threshold: int = 256 * 1024,
                 shm_capacity: Optional[int] = None,
                 inproc_cap_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        from ray_tpu.utils.config import get_config

        cfg = get_config()
        if shm_capacity is None:
            shm_capacity = cfg.object_store_memory_bytes
        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, ObjectState] = {}
        # Serializing everything (even in local mode) keeps semantics
        # identical to the distributed path: values are snapshots, and
        # non-serializable values fail at put-time, not at scale-up time.
        self._serialize_always = serialize_always
        self._shm_threshold = shm_threshold
        self._shm_capacity = shm_capacity
        self._shm = None
        self._shm_failed = False
        self._shm_lock = threading.Lock()
        # Spilling (parity: LocalObjectManager + external_storage.py):
        # when the in-process tier exceeds the cap, cold sealed objects
        # are fused into spill files and their bytes dropped.
        self._inproc_cap = (inproc_cap_bytes
                            if inproc_cap_bytes is not None
                            else cfg.object_store_inproc_cap_bytes)
        # Spill down to this fraction of cap (the low watermark).
        self._spill_low_frac = cfg.object_spill_threshold
        self._spill_dir = spill_dir or cfg.object_spill_dir or None
        self._inproc_bytes = 0
        self._storage = None
        # Called with an ObjectID when a reader hits a lost object;
        # the runtime hooks lineage reconstruction here (parity: the
        # plasma fetch failure that triggers ObjectRecoveryManager).
        self.lost_object_callback = None
        # Cross-node object plane hooks (multi-host runtime).
        # fetch_remote(node_hex, oid, size) -> framed bytes — pull the
        # primary copy from the owning node daemon's arena (parity:
        # PullManager fetching chunks from a remote object manager).
        # Raises on failure; the reader path then marks the object lost.
        self.fetch_remote = None
        # release_remote(node_hex, oid) — best-effort free of the
        # primary copy on its node when the owner's refcount hits zero.
        self.release_remote = None
        # In-flight remote fetch dedup: oid → Event (first reader pulls,
        # the rest wait; parity: pull_manager.h in-flight dedup).
        self._fetching: Dict[ObjectID, threading.Event] = {}
        # Ownership hooks (parity: the plasma/owner interplay in
        # reference_count.cc).  on_sealed(oid) fires once a value/error
        # is sealed — the runtime drops the task-return seal pin there.
        # on_nested(oid, [inner]) reports refs found inside a sealed
        # value so the counter can pin them.
        self.on_sealed = None
        self.on_nested = None
        # Tombstones of freed oids — a late get raises ObjectFreedError
        # instead of blocking forever.  Bounded (parity: the owner
        # keeps OUT_OF_SCOPE entries briefly).
        from ray_tpu.core.refcount import TombstoneSet

        self._freed = TombstoneSet(16384)
        # RLock: _spill_cold_objects holds it while lazily building the
        # storage via _external_storage (same lock).
        self._spill_lock = threading.RLock()
        self.spill_stats = {"spilled_objects": 0, "spilled_bytes": 0,
                            "restored_objects": 0, "restored_bytes": 0}

    def _external_storage(self):
        with self._spill_lock:
            if self._storage is None:
                import tempfile

                from ray_tpu.core.spill import FileSystemStorage

                d = self._spill_dir or tempfile.mkdtemp(prefix="raytpu-spill-")
                self._storage = FileSystemStorage(d)
            return self._storage

    def _shm_store(self):
        """Lazily build/attach the native store (lock: two racing large
        puts must not each create-and-unlink the segment); None if
        unbuildable (no g++) — callers fall back to in-process bytes."""
        with self._shm_lock:
            if self._shm is None and not self._shm_failed:
                try:
                    from ray_tpu.core.shm_store import SharedMemoryStore
                    import os

                    # Unique name per store instance: several runtimes in
                    # one process (tests) must not unlink each other.
                    seq = next(_shm_seq)
                    self._shm = SharedMemoryStore(
                        f"/raytpu-{os.getpid()}-{seq}",
                        capacity=self._shm_capacity, num_slots=65536,
                    )
                except Exception:
                    self._shm_failed = True
            return self._shm

    def _state(self, oid: ObjectID) -> ObjectState:
        with self._lock:
            st = self._objects.get(oid)
            if st is None:
                st = self._objects[oid] = ObjectState()
            return st

    # -- producer side -----------------------------------------------------

    def put_value(self, oid: ObjectID, value: Any) -> None:
        st = self._state(oid)
        nested = []
        if self._serialize_always:
            with collect_nested_refs() as nested:
                meta, buffers = serialize_parts(value)
            size = framed_size(meta, buffers)
            shm = (self._shm_store()
                   if size >= self._shm_threshold else None)
            if shm is not None:
                if try_shm_put(shm, oid.binary(), meta, buffers, size):
                    st.in_shm = True
                    st.shm_size = size
                else:
                    shm = None  # full/unavailable → local tier
            if shm is None:
                out = bytearray(size)
                write_framed(memoryview(out), meta, buffers)
                self._store_inline(st, bytes(out))
        else:
            st.in_band = value
        st.lost = False
        if nested and self.on_nested is not None:
            # Register nested pins BEFORE waking readers: a reader must
            # never deserialize inner refs the counter doesn't yet pin.
            self.on_nested(oid, nested)
        st.event.set()
        self._sealed(oid)
        if self._inproc_bytes > self._inproc_cap:
            self._spill_cold_objects()

    def _sealed(self, oid: ObjectID) -> None:
        cb = self.on_sealed
        if cb is not None:
            cb(oid)

    def _store_inline(self, st, data: bytes) -> None:
        """Account framed bytes into the in-process tier (shared by
        put_value's fallback and put_serialized)."""
        st.last_access = time.monotonic()
        with self._lock:
            # Re-puts (actor restart re-sealing its creation oid,
            # reconstruction) replace the old bytes — the ledger must
            # not count both copies.
            if st.value_bytes is not None:
                self._inproc_bytes -= len(st.value_bytes)
            st.value_bytes = data
            self._inproc_bytes += len(data)

    def _spill_cold_objects(self) -> None:
        """Spill least-recently-used sealed in-process objects until the
        tier is below ~80% of cap (parity: LocalObjectManager::
        SpillObjectsOfSize driven by the high/low watermark)."""
        low_water = int(self._inproc_cap * self._spill_low_frac)
        with self._spill_lock:
            with self._lock:
                if self._inproc_bytes <= low_water:
                    return
                victims = sorted(
                    ((oid, st) for oid, st in self._objects.items()
                     if st.value_bytes is not None and st.event.is_set()
                     and st.error is None),
                    key=lambda kv: kv[1].last_access,
                )
                batch = []
                freed = 0
                for oid, st in victims:
                    if self._inproc_bytes - freed <= low_water:
                        break
                    batch.append((oid, st, st.value_bytes))
                    freed += len(st.value_bytes)
            if not batch:
                return
            storage = self._external_storage()
            uris = storage.spill_objects(
                [(oid.binary(), payload) for oid, _, payload in batch]
            )
            orphaned: List[str] = []
            with self._lock:
                for (oid, st, payload), uri in zip(batch, uris):
                    if st.value_bytes is None:
                        # Raced with release(): it already adjusted the
                        # ledger; reclaim the just-written segment.
                        orphaned.append(uri)
                        continue
                    st.spilled_uri = uri
                    st.value_bytes = None
                    self._inproc_bytes -= len(payload)
                    self.spill_stats["spilled_objects"] += 1
                    self.spill_stats["spilled_bytes"] += len(payload)
            if orphaned:
                storage.delete(orphaned)

    def put_error(self, oid: ObjectID, error: BaseException) -> None:
        st = self._state(oid)
        st.error = error
        st.lost = False
        st.event.set()
        self._sealed(oid)

    # -- wire plane (multi-process workers) --------------------------------

    def shm_name(self) -> Optional[str]:
        """Force-build the native store and return its segment name so
        worker processes can attach (parity: plasma socket name handed
        to workers at registration)."""
        shm = self._shm_store()
        return shm.name if shm is not None else None

    @property
    def shm_threshold(self) -> int:
        return self._shm_threshold

    def put_serialized(self, oid: ObjectID, data) -> None:
        """Seal already-serialized (framed) bytes — the path for values
        produced in a worker process and shipped over the socket."""
        st = self._state(oid)
        data = bytes(data)
        size = len(data)
        shm = self._shm_store() if size >= self._shm_threshold else None
        if shm is not None:
            try:
                shm.put_bytes(oid.binary(), data)
                st.in_shm = True
                st.shm_size = size
                st.last_access = time.monotonic()
            except Exception:
                shm = None
        if shm is None:
            self._store_inline(st, data)
        st.lost = False
        st.event.set()
        self._sealed(oid)
        if self._inproc_bytes > self._inproc_cap:
            self._spill_cold_objects()
        if shm is not None:
            self._maybe_spill_arena()

    def mark_shm_sealed(self, oid: ObjectID, size: int) -> None:
        """A worker wrote+sealed this object directly into the shared
        arena; record the location and wake waiters."""
        st = self._state(oid)
        st.in_shm = True
        st.shm_size = size
        st.last_access = time.monotonic()
        st.lost = False
        st.event.set()
        self._sealed(oid)
        self._maybe_spill_arena()

    def _maybe_spill_arena(self) -> None:
        """Spill cold sealed ARENA objects to disk when the arena runs
        hot, instead of losing them to LRU eviction (parity: the
        reference spills FROM plasma — LocalObjectManager::
        SpillObjectsOfSize over plasma entries).  Objects currently
        pinned by readers are skipped (delete would EBUSY)."""
        shm = self._shm
        if shm is None:
            return
        try:
            stats = shm.stats()
        except OSError:
            return
        cap = stats["capacity"] or 1
        if stats["bytes_used"] / cap < self._spill_low_frac:
            return
        low_water = int(cap * max(0.0, self._spill_low_frac - 0.2))
        with self._spill_lock:
            with self._lock:
                victims = sorted(
                    ((oid, st) for oid, st in self._objects.items()
                     if st.in_shm and st.event.is_set()
                     and st.error is None and st.remote_node is None
                     and st.spilled_uri is None),
                    key=lambda kv: kv[1].last_access,
                )
            used = stats["bytes_used"]
            batch = []
            for oid, st in victims:
                if used <= low_water:
                    break
                try:
                    payload = shm.get_bytes(oid.binary(), timeout=0.0)
                except OSError:
                    continue
                batch.append((oid, st, payload))
                used -= len(payload)
            if not batch:
                return
            storage = self._external_storage()
            uris = storage.spill_objects(
                [(oid.binary(), payload) for oid, _, payload in batch]
            )
            orphaned: List[str] = []
            for (oid, st, payload), uri in zip(batch, uris):
                with self._lock:
                    if not st.in_shm or not st.event.is_set():
                        orphaned.append(uri)  # raced release/invalidate
                        continue
                    st.spilled_uri = uri
                    st.in_shm = False
                    self.spill_stats["spilled_objects"] += 1
                    self.spill_stats["spilled_bytes"] += len(payload)
                try:
                    shm.delete(oid.binary())
                except OSError:
                    # Pinned by a live reader: keep both copies; the
                    # arena copy goes with the pin, the spill file
                    # remains authoritative in our index.
                    pass
            if orphaned:
                storage.delete(orphaned)

    # -- cross-node object plane -------------------------------------------

    def mark_remote_sealed(self, oid: ObjectID, node_hex: str,
                           size: int) -> None:
        """The primary copy was sealed into a remote node daemon's arena
        (parity: the owner recording an object location from a remote
        plasma seal).  Local readers fetch lazily via ``fetch_remote``."""
        st = self._state(oid)
        st.remote_node = node_hex
        st.shm_size = size
        st.lost = False
        st.event.set()
        self._sealed(oid)

    def remote_location(self, oid: ObjectID) -> Optional[str]:
        with self._lock:
            st = self._objects.get(oid)
            return st.remote_node if st is not None else None

    def _materialize_remote(self, oid: ObjectID, st) -> None:
        """Pull a remote primary copy into the local tiers.  Dedups
        concurrent readers; on pull failure marks the object lost so
        the reader loop triggers lineage reconstruction."""
        with self._lock:
            node_hex = st.remote_node
            if node_hex is None or not st.event.is_set():
                return  # raced: someone else materialized or invalidated
            ev = self._fetching.get(oid)
            if ev is not None:
                waiter = True
            else:
                waiter = False
                ev = self._fetching[oid] = threading.Event()
            size = st.shm_size
        if waiter:
            ev.wait(300.0)
            return
        try:
            fetch = self.fetch_remote
            if fetch is None:
                raise OSError(f"no remote-fetch path for {oid.hex()}")
            data = fetch(node_hex, oid, size)
            # Admit into the local tiers WITHOUT re-firing seal hooks
            # (the object was already sealed once).
            shm = (self._shm_store()
                   if len(data) >= self._shm_threshold else None)
            admitted_shm = False
            if shm is not None:
                try:
                    shm.put_bytes(oid.binary(), bytes(data))
                    admitted_shm = True
                except Exception:
                    admitted_shm = False
            with self._lock:
                if st.remote_node != node_hex:
                    return  # invalidated mid-pull; drop our copy
                if admitted_shm:
                    st.in_shm = True
                    st.shm_size = len(data)
                else:
                    if st.value_bytes is not None:
                        self._inproc_bytes -= len(st.value_bytes)
                    st.value_bytes = bytes(data)
                    self._inproc_bytes += len(data)
                # remote_node stays set: the producing node still holds
                # the primary copy, and release() must free it there
                # when the refcount hits zero.  Read paths prefer the
                # local tiers once they exist.
                st.last_access = time.monotonic()
        except Exception:
            # Primary copy unreachable (node died mid-pull): invalidate
            # so readers trigger reconstruction instead of spinning.
            self.invalidate(oid)
        finally:
            with self._lock:
                self._fetching.pop(oid, None)
            ev.set()
            if self._inproc_bytes > self._inproc_cap:
                self._spill_cold_objects()

    def get_wire(self, oid: ObjectID, timeout: Optional[float] = None):
        """Blocking fetch of an object's WIRE representation for a
        worker: ("shm", size) — read it from the shared arena;
        ("b", bytes) — framed serialized payload; ("err", exc) — sealed
        error to re-raise.  Never deserializes the value (the worker
        does the one decode)."""
        if oid in self._freed:
            raise ObjectFreedError(oid.hex())
        st = self._state(oid)
        while True:
            ready, _ = self.wait([oid], 1, timeout)
            if not ready:
                raise GetTimeoutError(
                    f"get timed out after {timeout}s for {oid.hex()}"
                )
            with self._lock:
                if not st.event.is_set():
                    # invalidate() raced between wait and snapshot —
                    # loop back to the wait/reconstruction path (same
                    # defense as get()).
                    continue
                err = st.error
                if err is not None:
                    return ("err", err)
                if st.in_shm:
                    return ("shm", st.shm_size)
                vb = st.value_bytes
                spilled = st.spilled_uri
                in_band = st.in_band
                remote_only = (st.remote_node is not None and vb is None
                               and spilled is None and in_band is None)
            if remote_only:
                # Pull the primary copy local, then re-snapshot.
                self._materialize_remote(oid, st)
                continue
            break
        if vb is not None:
            st.last_access = time.monotonic()
            return ("b", vb)
        if spilled is not None:
            data = self._external_storage().restore(spilled)
            self.spill_stats["restored_objects"] += 1
            self.spill_stats["restored_bytes"] += len(data)
            return ("b", data)
        # in-band (serialize_always=False configurations): one pickle hop.
        from ray_tpu.utils.serialization import serialize_object

        return ("b", serialize_object(in_band))

    def get_wire_loc(self, oid: ObjectID, timeout: Optional[float] = None):
        """Like get_wire but NEVER pulls a remote primary copy local:
        returns ("at", (node_hex, size)) instead, so dispatch paths can
        ship a location and let the consuming node pull directly
        (A → B instead of A → head → B)."""
        if oid in self._freed:
            raise ObjectFreedError(oid.hex())
        st = self._state(oid)
        ready, _ = self.wait([oid], 1, timeout)
        if not ready:
            raise GetTimeoutError(
                f"get timed out after {timeout}s for {oid.hex()}"
            )
        with self._lock:
            if st.event.is_set() and st.error is None \
                    and st.remote_node is not None:
                return ("at", (st.remote_node, st.shm_size))
        return self.get_wire(oid, timeout)

    def read_range(self, oid: ObjectID, off: int, length: int) -> bytes:
        """Serve ``length`` framed bytes at ``off`` of a LOCAL copy —
        the serving side of the cross-node pull protocol (parity: the
        object manager answering Pull with ObjectChunk pushes,
        object_manager.h:117).  Zero-copy out of the arena; spilled
        objects restore through a short-lived cache so a chunked pull
        doesn't re-read the spill file per chunk."""
        st = self._state(oid)
        if not st.event.is_set():
            raise OSError(f"object {oid.hex()} not sealed here")
        with self._lock:
            in_shm = st.in_shm
            vb = st.value_bytes
            spilled = st.spilled_uri
        if in_shm:
            shm = self._shm_store()
            if shm is not None:
                pb = shm.get(oid.binary(), timeout=0.0)
                return bytes(pb.view[off:off + length])
        if vb is None and spilled is not None:
            vb = self._restored_for_pull(oid, spilled)
        if vb is not None:
            return bytes(vb[off:off + length])
        raise OSError(f"object {oid.hex()}: no local bytes to serve")

    _PULL_CACHE_CAP = 256 << 20  # restored-payload cache, across pulls

    def _restored_for_pull(self, oid: ObjectID, spilled: str) -> bytes:
        """Restore a spilled payload for chunked serving, through a
        small lock-protected cache so one pull's chunks share one disk
        read.  Size-capped: oversized payloads serve uncached, and the
        oldest entries evict to admit new ones."""
        lock = getattr(self, "_pull_cache_lock", None)
        if lock is None:
            lock = self._pull_cache_lock = threading.Lock()
        with lock:
            cache = getattr(self, "_pull_cache", None)
            if cache is None:
                # oid → (bytes, expiry); insertion-ordered for eviction.
                cache = self._pull_cache = {}
            hit = cache.get(oid)
            now = time.monotonic()
            if hit is not None and hit[1] >= now:
                return hit[0]
        data = self._external_storage().restore(spilled)
        self.spill_stats["restored_objects"] += 1
        self.spill_stats["restored_bytes"] += len(data)
        if len(data) > self._PULL_CACHE_CAP:
            return data  # too big to cache; each chunk re-reads
        with lock:
            now = time.monotonic()
            for k in [k for k, (_, exp) in cache.items() if exp < now]:
                del cache[k]
            total = sum(len(v) for v, _ in cache.values())
            while cache and total + len(data) > self._PULL_CACHE_CAP:
                _, (old, _exp) = cache.popitem()
                total -= len(old)
            cache[oid] = (data, now + 30.0)
        return data

    def is_freed(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._freed

    def put_error_if_pending(self, oid: ObjectID,
                             error: BaseException) -> bool:
        """Seal an error only if the object is still unsealed — used by
        failure paths that must not clobber already-produced stream
        items.  Freed (tombstoned) oids are never resurrected."""
        if oid in self._freed:
            return False
        st = self._state(oid)
        with self._lock:
            if st.event.is_set():
                return False
            st.error = error
            st.lost = False
            st.event.set()
        self._sealed(oid)
        return True

    # -- consumer side -----------------------------------------------------

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            st = self._objects.get(oid)
        return bool(st and st.event.is_set())

    def peek_error(self, oid: ObjectID) -> Optional[BaseException]:
        """Non-blocking: the stored error, if this object resolved to one."""
        with self._lock:
            st = self._objects.get(oid)
        return st.error if st is not None and st.event.is_set() else None

    def get(self, oid: ObjectID, timeout: Optional[float] = None) -> Any:
        if oid in self._freed:
            raise ObjectFreedError(oid.hex())
        st = self._state(oid)
        deadline = None if timeout is None else time.monotonic() + timeout
        return self._get_loop(st, oid, timeout, deadline)

    def _get_loop(self, st, oid: ObjectID, timeout: Optional[float],
                  deadline: Optional[float]) -> Any:
        while True:
            if st.lost and self.lost_object_callback is not None:
                # Lazy reconstruction on fetch (parity:
                # ObjectRecoveryManager::RecoverObject on pull failure).
                self.lost_object_callback(oid)
            slice_t = 0.5 if deadline is None else \
                max(0.0, min(0.5, deadline - time.monotonic()))
            if not st.event.wait(slice_t):
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        f"get timed out after {timeout}s for {oid.hex()}"
                    )
                continue
            # Snapshot under the lock: concurrent spill or invalidate
            # may flip the representation between our checks.
            with self._lock:
                if not st.event.is_set():
                    continue  # invalidated between wait and snapshot
                err = st.error
                shm_flag = st.in_shm
                vb = st.value_bytes
                spilled = st.spilled_uri
                in_band = st.in_band
                remote_only = (st.remote_node is not None and not shm_flag
                               and vb is None and spilled is None
                               and in_band is None)
            if err is not None:
                raise err
            if remote_only:
                # Primary copy is on a remote node: pull it local first
                # (dedup'd across concurrent readers), then re-snapshot.
                self._materialize_remote(oid, st)
                continue
            if shm_flag:
                shm = self._shm_store()
                if shm is None:  # store closed under a racing reader
                    raise ObjectLostError(
                        f"object {oid.hex()}: shared-memory store is closed"
                    )
                try:
                    pinned = shm.get(oid.binary(), timeout=0.0)
                except OSError:
                    raise ObjectLostError(
                        f"object {oid.hex()} was evicted from the "
                        f"shared-memory store (size {st.shm_size}) — "
                        f"increase capacity or release refs sooner"
                    ) from None
                # Zero-copy: deserialized arrays alias the arena through
                # the pinned exporter; the native refcount drops
                # automatically when the last view is garbage-collected
                # (parity: plasma buffers unpin on Python-object GC).
                return deserialize_object(pinned.view)
            if vb is not None:
                st.last_access = time.monotonic()
                return deserialize_object(vb)
            if spilled is not None:
                # Restore from disk (parity: LocalObjectManager restore
                # via IO workers; here a direct read).  The restored
                # bytes are not re-admitted — a hot object will be
                # re-put by its producer pattern, and not re-admitting
                # avoids spill↔restore thrash under sustained pressure.
                try:
                    data = self._external_storage().restore(spilled)
                except OSError:
                    # The spilled_uri snapshot raced a concurrent
                    # invalidate() (node death deletes spill files).  If
                    # the representation changed in that window — the
                    # object was marked lost, or reconstruction already
                    # re-sealed it — loop back to the wait/reconstruct
                    # path instead of surfacing a spurious
                    # ObjectLostError.
                    with self._lock:
                        changed = (st.lost or not st.event.is_set()
                                   or st.spilled_uri != spilled)
                    if changed:
                        continue
                    raise ObjectLostError(
                        f"object {oid.hex()}: spilled copy unreadable"
                    ) from None
                self.spill_stats["restored_objects"] += 1
                self.spill_stats["restored_bytes"] += len(data)
                return deserialize_object(data)
            return in_band

    def wait(
        self,
        oids: List[ObjectID],
        num_returns: int,
        timeout: Optional[float],
    ) -> Tuple[List[ObjectID], List[ObjectID]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectID] = []
        pending = list(oids)
        while len(ready) < num_returns:
            progressed = False
            for oid in list(pending):
                if oid in self._freed:
                    # Freed objects count as ready: the follow-up get
                    # raises ObjectFreedError immediately (no hang).
                    ready.append(oid)
                    pending.remove(oid)
                    progressed = True
                    if len(ready) >= num_returns:
                        break
                    continue
                st = self._state(oid)
                if st.event.is_set():
                    ready.append(oid)
                    pending.remove(oid)
                    progressed = True
                    if len(ready) >= num_returns:
                        break
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if not progressed:
                if self.lost_object_callback is not None:
                    for oid in pending:
                        if self._state(oid).lost:
                            self.lost_object_callback(oid)
                # Block on one pending object with a bounded slice.
                slice_t = 0.05
                if deadline is not None:
                    slice_t = min(slice_t, max(0.0, deadline - time.monotonic()))
                if pending:
                    self._state(pending[0]).event.wait(slice_t)
        return ready, pending

    def invalidate(self, oid: ObjectID) -> bool:
        """Un-seal a sealed object, dropping its bytes everywhere —
        models loss of the primary copy when its node dies (parity: the
        owner's view after plasma loss, before ObjectRecoveryManager
        rebuilds it).  Readers blocked in get() stay blocked until a
        reconstruction re-seals the id.  Returns False if the object
        isn't currently sealed."""
        with self._lock:
            st = self._objects.get(oid)
            if st is None or not st.event.is_set():
                return False
            if st.value_bytes is not None:
                self._inproc_bytes -= len(st.value_bytes)
            spilled, st.spilled_uri = st.spilled_uri, None
            was_shm, st.in_shm = st.in_shm, False
            st.value_bytes = None
            st.in_band = None
            st.error = None
            st.remote_node = None
            st.lost = True
            st.event.clear()
        if spilled is not None and self._storage is not None:
            self._storage.delete([spilled])
        if was_shm and self._shm is not None:
            try:
                self._shm.delete(oid.binary())
            except OSError:
                pass
        return True

    def release(self, oid: ObjectID, tombstone: bool = False) -> None:
        with self._lock:
            st = self._objects.pop(oid, None)
            if tombstone:
                self._freed.add(oid)
            if st is not None and st.value_bytes is not None:
                self._inproc_bytes -= len(st.value_bytes)
                # Null the bytes so an in-flight spill of this object
                # detects the release instead of double-decrementing.
                st.value_bytes = None
        if st is not None and st.spilled_uri is not None \
                and self._storage is not None:
            # Owner released the object → spilled bytes are deleted
            # (parity: LocalObjectManager delete-spilled-on-free).
            self._storage.delete([st.spilled_uri])
        if st is not None and st.in_shm and self._shm is not None:
            try:
                # EBUSY while readers still hold views — their GC
                # finalizers drop the pins and LRU reclaims the block.
                self._shm.delete(oid.binary())
            except OSError:
                pass
        if st is not None and self.release_remote is not None \
                and (st.remote_node is not None
                     or st.in_shm or st.shm_size > 0):
            # Free every node-side copy: the primary AND any replicas
            # consumer daemons pulled (the hook broadcasts — only
            # arena-class objects ever enter daemon stores, so small
            # in-band releases cost nothing).
            self.release_remote(st.remote_node, oid)

    def close(self) -> None:
        if self._shm is not None:
            # keep_mapping: readers may still hold zero-copy arrays into
            # the arena; the name is unlinked, the mapping lives until
            # process exit.
            self._shm.close(unlink=True, keep_mapping=True)
            self._shm = None
        self._shm_failed = True  # don't resurrect after shutdown

    def inventory(self) -> List[Tuple[bytes, int]]:
        """(oid_binary, servable_size) for every object whose bytes this
        store can serve over the pull plane (shm, in-process, or
        spilled).  Used by a rejoining node daemon to re-advertise its
        arena to a restarted head (parity: a raylet re-reporting object
        locations to a recovered GCS)."""
        from ray_tpu.core.spill import FileSystemStorage

        with self._lock:
            items = list(self._objects.items())
        out: List[Tuple[bytes, int]] = []
        for oid, st in items:
            if not st.event.is_set() or st.error is not None:
                continue
            if st.in_shm:
                out.append((oid.binary(), st.shm_size))
            elif st.value_bytes is not None:
                out.append((oid.binary(), len(st.value_bytes)))
            elif st.spilled_uri is not None:
                try:
                    _, _, size = FileSystemStorage._parse(st.spilled_uri)
                except ValueError:
                    continue
                out.append((oid.binary(), size))
        return out

    def entries(self) -> List[Dict[str, Any]]:
        """Per-object rows for the state API (parity: `ray list objects`
        / the cluster reference table behind `ray memory`)."""
        with self._lock:
            items = list(self._objects.items())
        out = []
        for oid, st in items:
            if st.error is not None:
                tier, size = "ERROR", 0
            elif st.remote_node is not None:
                tier, size = "REMOTE", st.shm_size
            elif st.in_shm:
                tier, size = "SHARED_MEMORY", st.shm_size
            elif st.value_bytes is not None:
                tier, size = "IN_PROCESS", len(st.value_bytes)
            elif st.spilled_uri is not None:
                tier, size = "SPILLED", 0
            elif st.event.is_set():
                tier, size = "IN_BAND", 0
            else:
                tier, size = "PENDING", 0
            out.append({
                "object_id": oid.hex(),
                "task_id": oid.task_id().hex(),
                "tier": tier,
                "size_bytes": size,
                "sealed": st.event.is_set(),
                "is_error": st.error is not None,
            })
        return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            sealed = sum(1 for s in self._objects.values() if s.event.is_set())
            nbytes = sum(
                len(s.value_bytes) for s in self._objects.values()
                if s.value_bytes is not None
            )
            out = {
                "num_objects": len(self._objects),
                "num_sealed": sealed,
                "bytes": nbytes,
            }
            out.update(self.spill_stats)
        if self._shm is not None:
            out["shm"] = self._shm.stats()
        return out
