"""In-process object store (local runtime backend).

Semantics parity with the reference's two-tier store — the in-process
memory store for small/inlined values (ray:
src/ray/core_worker/store_provider/memory_store/memory_store.h:43) and
plasma for large ones (plasma/store.h:55): objects are immutable,
created-then-sealed, readable by many, and survive until released.

This Python implementation is the single-process backend; the C++
shared-memory store (ray_tpu/_native) plugs in behind the same
interface for the multi-process runtime.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ray_tpu.core.exceptions import GetTimeoutError, ObjectLostError
from ray_tpu.core.object_ref import ObjectState
from ray_tpu.utils.ids import ObjectID
from ray_tpu.utils.serialization import deserialize_object, serialize_object


class LocalObjectStore:
    """Thread-safe map ObjectID → sealed value (serialized or in-band)."""

    def __init__(self, *, serialize_always: bool = True):
        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, ObjectState] = {}
        # Serializing everything (even in local mode) keeps semantics
        # identical to the distributed path: values are snapshots, and
        # non-serializable values fail at put-time, not at scale-up time.
        self._serialize_always = serialize_always

    def _state(self, oid: ObjectID) -> ObjectState:
        with self._lock:
            st = self._objects.get(oid)
            if st is None:
                st = self._objects[oid] = ObjectState()
            return st

    # -- producer side -----------------------------------------------------

    def put_value(self, oid: ObjectID, value: Any) -> None:
        st = self._state(oid)
        if self._serialize_always:
            st.value_bytes = serialize_object(value)
        else:
            st.in_band = value
        st.event.set()

    def put_error(self, oid: ObjectID, error: BaseException) -> None:
        st = self._state(oid)
        st.error = error
        st.event.set()

    # -- consumer side -----------------------------------------------------

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            st = self._objects.get(oid)
        return bool(st and st.event.is_set())

    def peek_error(self, oid: ObjectID) -> Optional[BaseException]:
        """Non-blocking: the stored error, if this object resolved to one."""
        with self._lock:
            st = self._objects.get(oid)
        return st.error if st is not None and st.event.is_set() else None

    def get(self, oid: ObjectID, timeout: Optional[float] = None) -> Any:
        st = self._state(oid)
        if not st.event.wait(timeout):
            raise GetTimeoutError(f"get timed out after {timeout}s for "
                                  f"{oid.hex()}")
        if st.error is not None:
            raise st.error
        if st.value_bytes is not None:
            return deserialize_object(st.value_bytes)
        return st.in_band

    def wait(
        self,
        oids: List[ObjectID],
        num_returns: int,
        timeout: Optional[float],
    ) -> Tuple[List[ObjectID], List[ObjectID]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectID] = []
        pending = list(oids)
        while len(ready) < num_returns:
            progressed = False
            for oid in list(pending):
                st = self._state(oid)
                if st.event.is_set():
                    ready.append(oid)
                    pending.remove(oid)
                    progressed = True
                    if len(ready) >= num_returns:
                        break
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if not progressed:
                # Block on one pending object with a bounded slice.
                slice_t = 0.05
                if deadline is not None:
                    slice_t = min(slice_t, max(0.0, deadline - time.monotonic()))
                if pending:
                    self._state(pending[0]).event.wait(slice_t)
        return ready, pending

    def release(self, oid: ObjectID) -> None:
        with self._lock:
            self._objects.pop(oid, None)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            sealed = sum(1 for s in self._objects.values() if s.event.is_set())
            nbytes = sum(
                len(s.value_bytes) for s in self._objects.values()
                if s.value_bytes is not None
            )
            return {
                "num_objects": len(self._objects),
                "num_sealed": sealed,
                "bytes": nbytes,
            }
