"""Placement groups: gang reservation of resource bundles across nodes.

Parity with the reference's placement-group subsystem
(ray: python/ray/util/placement_group.py:41,146 — PlacementGroup handle +
factory; src/ray/gcs/gcs_server/gcs_placement_group_manager.h:225 and
gcs_placement_group_scheduler.cc — bundle reservation with PACK / SPREAD /
STRICT_PACK / STRICT_SPREAD policies, raylet/scheduling/policy/
bundle_scheduling_policy.h:31-98).

TPU twist: nodes labeled with an integer ``ici_index`` are considered in
coordinate order during reservation, so bundles of one group land on a
contiguous slice block along the ICI topology (slice-aware gang
scheduling — the reference only sketches TPU pod-head resources in
_private/accelerator.py:176-191).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu.utils.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD",
                    # TPU gang placement: bundles land on a contiguous
                    # axis-aligned rectangle of one slice's ICI grid
                    # (nodes labeled ici_coord="x,y"), or stay pending —
                    # fragmented placements are rejected.
                    "ICI_CONTIGUOUS")


@dataclasses.dataclass
class Bundle:
    """One reserved resource bundle, placed on exactly one node."""

    index: int
    resources: Dict[str, float]
    node_id: Any = None  # NodeID once reserved
    # Per-bundle ledger of what's still free inside the reservation.
    available: Dict[str, float] = dataclasses.field(default_factory=dict)
    lock: threading.Lock = dataclasses.field(default_factory=threading.Lock,
                                             repr=False)

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        with self.lock:
            if all(self.available.get(k, 0) >= v - 1e-9
                   for k, v in demand.items()):
                for k, v in demand.items():
                    self.available[k] = self.available.get(k, 0) - v
                return True
            return False

    def release(self, demand: Dict[str, float]) -> None:
        with self.lock:
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0) + v


class PlacementGroup:
    """Client handle to a placement group (parity: util/placement_group.py:41)."""

    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self.name = name

    def ready(self):
        """ObjectRef resolving once all bundles are reserved."""
        from ray_tpu.core import api

        return api.runtime().pg_ready_ref(self.id)

    def wait(self, timeout: Optional[float] = None) -> bool:
        from ray_tpu.core import api

        try:
            api.runtime().get(self.ready(), timeout)
            return True
        except TimeoutError:
            return False

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __repr__(self):
        return (f"PlacementGroup(id={self.id.hex()[:8]}, "
                f"strategy={self.strategy}, bundles={self.bundle_specs})")


def placement_group(bundles: Sequence[Dict[str, float]], *,
                    strategy: str = "PACK", name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    """Reserve resource bundles across the cluster
    (parity: util/placement_group.py:146)."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    bundles = [dict(b) for b in bundles]
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b!r}")
    from ray_tpu.core import api

    return api.runtime().create_placement_group(bundles, strategy, name,
                                                lifetime)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core import api

    api.runtime().remove_placement_group(pg.id)


def get_placement_group(name: str) -> PlacementGroup:
    from ray_tpu.core import api

    return api.runtime().get_named_placement_group(name)


# ---------------------------------------------------------------------------
# Scheduling strategies (parity: python/ray/util/scheduling_strategies.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: PlacementGroup
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    node_id: Any  # NodeID or its hex string
    soft: bool = False


@dataclasses.dataclass
class NodeLabelSchedulingStrategy:
    hard: Dict[str, str] = dataclasses.field(default_factory=dict)
    soft: Dict[str, str] = dataclasses.field(default_factory=dict)


# "DEFAULT" (hybrid pack-then-spread) and "SPREAD" are passed as strings.
SchedulingStrategyT = Any
