"""Public runtime API — init / remote / get / put / wait.

Parity with the reference's driver API
(ray: python/ray/_private/worker.py — init:1139, get:2481, put:2590,
wait:2653, remote:3027, shutdown:1716, kill, get_actor).
"""

from __future__ import annotations

import atexit
import inspect
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.core.actor import ActorClass, ActorHandle, method  # noqa: F401
from ray_tpu.core.exceptions import RuntimeNotInitializedError
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.runtime import LocalRuntime
from ray_tpu.utils.config import get_config

_runtime: Optional[LocalRuntime] = None
_runtime_lock = threading.Lock()


def runtime() -> LocalRuntime:
    global _runtime
    rt = _runtime
    if rt is None:
        raise RuntimeNotInitializedError()
    return rt


def is_initialized() -> bool:
    return _runtime is not None


def init(
    *,
    resources: Optional[Dict[str, float]] = None,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    system_config: Optional[Dict[str, Any]] = None,
    ignore_reinit_error: bool = False,
) -> LocalRuntime:
    """Start (or connect to) the runtime.

    Currently single-node: one in-process runtime hosting tasks/actors
    with logical resources.  TPU chips are auto-detected into the "TPU"
    resource (parity: _private/accelerator.py TPU detection).
    """
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            if ignore_reinit_error:
                return _runtime
            raise RuntimeError("ray_tpu.init() called twice — pass "
                               "ignore_reinit_error=True to allow")
        if system_config:
            get_config().update(system_config)
        total = dict(resources or {})
        labels = None
        if num_cpus is not None:
            total["CPU"] = float(num_cpus)
        if num_tpus is not None:
            total["TPU"] = float(num_tpus)
        elif "TPU" not in total:
            # Full detection path (parity: _private/accelerator.py):
            # chip count, version resource, slice-head resource, ICI
            # topology labels.
            from ray_tpu.utils.accelerator import node_resources_and_labels

            extra, labels = node_resources_and_labels()
            for k, v in extra.items():
                total.setdefault(k, v)
            labels = labels or None
        _runtime = LocalRuntime(resources=total, labels=labels)
        # Always-on telemetry history plane: the driver samples its own
        # registry; worker points arrive via reply piggyback
        # (runtime.apply_ref_batches → timeseries.ingest).
        from ray_tpu.util import timeseries

        timeseries.ensure_started()
        atexit.register(shutdown)
        return _runtime


def shutdown() -> None:
    global _runtime
    with _runtime_lock:
        rt = _runtime
        _runtime = None
    if rt is not None:
        rt.shutdown()


def remote(*args, **kwargs):
    """@remote decorator for functions and classes (parity: ray.remote)."""

    def make(target):
        if inspect.isclass(target):
            return ActorClass(target, **kwargs)
        return RemoteFunction(target, **kwargs)

    if len(args) == 1 and not kwargs and (inspect.isfunction(args[0])
                                          or inspect.isclass(args[0])):
        return make(args[0])
    if args:
        raise TypeError("@remote takes only keyword options, e.g. "
                        "@remote(num_cpus=2)")
    return make


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *,
        timeout: Optional[float] = None):
    _check_refs(refs)
    return runtime().get(refs, timeout)


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put of an ObjectRef is not allowed")
    return runtime().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait expects a list of ObjectRefs")
    _check_refs(refs)
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    return runtime().wait(refs, num_returns, timeout, fetch_local)


def _check_refs(refs):
    if isinstance(refs, ObjectRef):
        return
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"expected ObjectRef, got {type(r).__name__}")


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    runtime().kill_actor(actor._actor_id, no_restart)


def get_actor(name: str) -> ActorHandle:
    actor_id, cls_name, table, cgroups = runtime().named_actor_handle(name)
    return ActorHandle(actor_id, cls_name, table,
                       method_cgroups=cgroups)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    """Cancel the task producing ``ref`` (parity: ray.cancel).  Pending
    tasks never run; running tasks are interrupted cooperatively, or
    hard-killed with force=True in process mode.  get() of a cancelled
    ref raises TaskCancelledError; cancelled tasks never retry."""
    if not isinstance(ref, ObjectRef):
        raise TypeError(f"cancel expects an ObjectRef, got "
                        f"{type(ref).__name__}")
    runtime().cancel(ref.id, force=force)


def nodes() -> List[Dict[str, Any]]:
    return runtime().nodes()


def cluster_resources() -> Dict[str, float]:
    return runtime().cluster_resources()


def available_resources() -> Dict[str, float]:
    return runtime().available_resources()
