"""Daemon-local task dispatch over a synced cluster resource view.

Parity: the reference's Ray Syncer + raylet-local scheduling.  There,
raylets own their node's resources, gossip resource views through the
GCS (ray: src/ray/common/ray_syncer/ray_syncer.h:86), and a worker's
nested submission is scheduled by its LOCAL raylet — the centralized
control plane is off the task hot path.  Here the head owns the
authoritative ledgers (single-writer), so the sync direction inverts:
the head broadcasts seq-versioned per-node availability to every
daemon (`resource_view` casts from NodeServer), and each daemon runs a
LOCAL fast path for its workers' nested submissions against its own
slice of that view:

  worker submit_task → daemon eligibility check → lease a LOCAL worker
  → push → seal locally, with one fire-and-forget `local_task` cast to
  the head (ordered ahead of every later op on the same channel) that
  registers lineage, return-oid pins, arg pins, events, and the ledger
  debit.  The head round-trip leaves the submit critical path.

Consistency model (the reference's, deliberately): scheduling decisions
use an eventually-consistent view, bounded overcommit within one sync
period; the hard limits are enforced by the daemon's worker-pool cap
and the unacked-delta ledger below.  Ordering makes the bookkeeping
race-free: the `local_task` cast is sent on the daemon→head channel
BEFORE the submit reply, so the head registers pins before it can see
any ref-drop or get for the minted ids.

Failure model: an app exception seals an error on the return oids (cast
`local_task_failed`, retryable=False); a local worker crash hands the
task BACK to the head (retryable=True) which re-enqueues it through the
normal scheduler — the head hydrates fn/args from the cast's spec, so
retries and daemon-death recovery reuse the existing retry/lineage
machinery (`runtime.finish_external_task`).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.utils.ids import ActorID, ObjectID, TaskID


class UnackedLedger:
    """Local resource deltas not yet reflected in the head's view.

    Every local dispatch debits, every completion credits; each delta
    carries a monotonically increasing ``lseq`` that rides its cast to
    the head.  The head's view-sync echoes the highest lseq it has
    applied for this node, at which point the delta is part of the
    synced availability and is dropped here.  Effective availability =
    synced - sum(unacked debits) + sum(unacked credits).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._lseq = 0
        # (lseq, sign, demand) — sign -1 debit, +1 credit.
        self._deltas: "collections.deque" = collections.deque()

    def next_delta(self, sign: int, demand: Dict[str, float]) -> int:
        with self._lock:
            self._lseq += 1
            self._deltas.append((self._lseq, sign, demand))
            return self._lseq

    def ack(self, lseq: int) -> None:
        with self._lock:
            while self._deltas and self._deltas[0][0] <= lseq:
                self._deltas.popleft()

    def effective(self, synced: Dict[str, float]) -> Dict[str, float]:
        out = dict(synced)
        with self._lock:
            for _, sign, demand in self._deltas:
                for k, v in demand.items():
                    out[k] = out.get(k, 0.0) + sign * v
        return out

    def reset(self) -> None:
        with self._lock:
            self._deltas.clear()


class LocalDispatcher:
    """Per-daemon fast path for nested task submissions."""

    def __init__(self, daemon):
        self.d = daemon
        self.ledger = UnackedLedger()
        self._view_lock = threading.Lock()
        self._view: Optional[Dict[str, Dict[str, Dict[str, float]]]] = None
        self._view_ts = 0.0
        self._inflight_lock = threading.Lock()
        # task_bin -> {"wh": worker handle or None, "cancelled": bool}
        self._inflight: Dict[bytes, Dict[str, Any]] = {}
        from ray_tpu.core.runtime import _CachedThreadPool

        self._exec = _CachedThreadPool(name="local-dispatch")
        self._last_reclaim = 0.0
        self.stats_counters = {"dispatched": 0, "forwarded": 0,
                               "completed": 0, "failed": 0,
                               "returned_to_head": 0}

    # -- view sync ---------------------------------------------------------

    def on_view(self, nodes: Dict[str, Dict[str, Dict[str, float]]],
                ack_lseq: int) -> None:
        with self._view_lock:
            self._view = nodes
            self._view_ts = time.monotonic()
        self.ledger.ack(ack_lseq)

    def view_fresh(self, max_age: float = 5.0) -> bool:
        with self._view_lock:
            return (self._view is not None
                    and time.monotonic() - self._view_ts <= max_age)

    def cluster_available(self) -> Optional[Dict[str, float]]:
        """Cluster-wide availability from the synced view (serves a
        worker's ``available_resources()`` without a head RPC); None
        when the view is stale."""
        if not self.view_fresh():
            return None
        with self._view_lock:
            nodes = dict(self._view)
        total: Dict[str, float] = {}
        for hexid, entry in nodes.items():
            avail = entry.get("available") or {}
            if hexid == self.d.node_hex:
                avail = self.ledger.effective(avail)
            for k, v in avail.items():
                total[k] = total.get(k, 0.0) + max(0.0, v)
        return total

    def reset(self) -> None:
        """Head restart: in-flight local tasks died with the previous
        epoch's workers (the rejoin contract kills them), their casts
        are gone with the old channel — drop all local state and stay
        off the fast path until the new head's first view sync."""
        with self._view_lock:
            self._view = None
        self.ledger.reset()
        with self._inflight_lock:
            self._inflight.clear()

    # -- submission --------------------------------------------------------

    def maybe_submit(self, msg: Dict[str, Any],
                     worker_chan) -> Optional[Dict[str, Any]]:
        """Local fast path for one worker ``submit_task`` op.  Returns
        the submit reply, or None to forward to the head (ineligible,
        stale view, no capacity — the head path is always correct)."""
        opts = msg.get("options")
        deps = msg.get("deps")
        if opts is None or deps is None:
            return None  # pre-deps client shape: head path
        if (opts.num_returns == "streaming" or opts.runtime_env
                or opts.effective_strategy() != "DEFAULT"):
            return None
        if not self.view_fresh():
            return None
        demand = opts.resource_demand()
        with self._view_lock:
            mine = (self._view or {}).get(self.d.node_hex)
        if mine is None:
            return None
        avail = self.ledger.effective(mine.get("available") or {})
        for k, v in demand.items():
            if v > 0 and avail.get(k, 0.0) < v:
                self.stats_counters["forwarded"] += 1
                return None
        # Dependencies must be locally sealed: the head path owns
        # parking/wakeup; a blocked local worker would be a wasted slot.
        store = self.d.store
        for b in deps:
            if not store.contains(ObjectID(b)):
                self.stats_counters["forwarded"] += 1
                return None
        wh = self.d.pool.lease(dedicated=False, block=False)
        if wh is None:
            # The pool is often exhausted not by running tasks but by
            # the HEAD's cached idle leases (lease pipelining keeps
            # released workers head-leased for remote_lease_idle_s).
            # Ask it to return the idle ones so the NEXT local submit
            # finds capacity; rate-limited to one nudge per 100 ms.
            now = time.monotonic()
            if now - self._last_reclaim > 0.1:
                self._last_reclaim = now
                self.d.head.cast("reclaim_leases")
            self.stats_counters["forwarded"] += 1
            return None
        self.d._hook_death(wh)

        task_id = TaskID.of(ActorID.nil_for_job(self.d.job_id))
        n_returns = opts.num_returns
        return_bins = [
            ObjectID.for_task_return(task_id, i).binary()
            for i in range(n_returns)
        ]
        from ray_tpu.core.worker_pool import _wkey

        submit_key = self.d._key_prefix + _wkey(worker_chan)
        lseq = self.ledger.next_delta(-1, demand)
        try:
            # MUST precede the reply: same-channel FIFO guarantees the
            # head pins returns/args before any later ref-drop or get.
            self.d.head.cast(
                "local_task", task=task_id.binary(), returns=return_bins,
                spec=msg["spec"], options=opts, deps=deps,
                pins=msg.get("pins") or [], demand=demand,
                wkey=submit_key, trace_ctx=msg.get("trace_ctx"),
                lseq=lseq,
            )
        except Exception:
            self.ledger.ack(lseq)  # drop the delta; nothing registered
            self.d.pool.release(wh)
            return None
        with self._inflight_lock:
            self._inflight[task_id.binary()] = {"wh": wh,
                                                "cancelled": False}
        self.stats_counters["dispatched"] += 1
        self._exec.submit(
            lambda: self._run(task_id, wh, msg, return_bins, demand))
        return {"oids": return_bins}

    # -- execution ---------------------------------------------------------

    def _run(self, task_id: TaskID, wh, msg: Dict[str, Any],
             return_bins: List[bytes], demand: Dict[str, float]) -> None:
        from ray_tpu.core.exceptions import WorkerDiedError
        from ray_tpu.core.wire import ChannelClosedError

        opts = msg["options"]
        task_bin = task_id.binary()
        rep = None
        err: Optional[BaseException] = None
        retryable = False
        try:
            rep = wh.call(
                "task", spec=msg["spec"], name=opts.name or "nested",
                fn_hash=None, fn_blob=None, streaming=False,
                task=task_bin, num_returns=opts.num_returns,
                returns=return_bins, env=None,
                trace_ctx=msg.get("trace_ctx"),
            )
        except (WorkerDiedError, ChannelClosedError) as e:
            # Infra failure: hand the task back to the head, which
            # re-enqueues through the normal scheduler (any node).
            err, retryable = e, True
        except BaseException as e:
            err, retryable = e, False  # app exception → seal error
        finally:
            try:
                self.d.pool.release(wh)
            except Exception:
                pass
            with self._inflight_lock:
                entry = self._inflight.pop(task_bin, None)
        lseq = self.ledger.next_delta(+1, demand)
        if rep is not None:
            # Local store index first (authority for peer pulls and
            # local gets), then the owner-side seal at the head.
            for oid_bin, (kind, payload) in zip(return_bins,
                                                rep.get("results") or ()):
                if kind == "shm":
                    self.d.store.mark_shm_sealed(ObjectID(oid_bin), payload)
            self.stats_counters["completed"] += 1
            self.d.head.cast("local_task_done", task=task_bin,
                             returns=return_bins, rep=rep,
                             exec_wkey=self.d._worker_key(wh), lseq=lseq)
            return
        if entry is not None and entry.get("cancelled"):
            retryable = False  # cancelled tasks never retry
        if retryable:
            self.stats_counters["returned_to_head"] += 1
        else:
            self.stats_counters["failed"] += 1
        self.d.head.cast("local_task_failed", task=task_bin,
                         returns=return_bins, error=err,
                         retryable=retryable, lseq=lseq)

    # -- cancellation ------------------------------------------------------

    def cancel(self, task_bin: bytes, force: bool) -> None:
        with self._inflight_lock:
            entry = self._inflight.get(task_bin)
            if entry is None:
                return
            entry["cancelled"] = True
            wh = entry.get("wh")
        if wh is None:
            return
        try:
            if force:
                wh.terminate(graceful=False)
            else:
                wh.call("cancel", task=task_bin)
        except Exception:
            pass

    def stats(self) -> Dict[str, Any]:
        with self._inflight_lock:
            inflight = len(self._inflight)
        return {**self.stats_counters, "inflight": inflight,
                "view_fresh": self.view_fresh()}
