"""Streaming object-ref generators.

Parity with the reference's streaming generators
(ray: python/ray/_raylet.pyx — StreamingObjectRefGenerator:267, the
streaming-generator task executor :918): a task or actor method
declared ``num_returns="streaming"`` yields values that are sealed into
the store one at a time, and the caller iterates ``ObjectRef``s while
the producer is still running.  The end of the stream is an in-store
sentinel at the index after the last yield (parity: the
end-of-stream error object the reference appends).

Generator task retries are not supported (the consumer may already
have observed a prefix of the stream); submission forces
``max_retries=0`` — stricter than the reference, which replays with
idempotency caveats.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.utils.ids import ObjectID, TaskID

STREAMING = "streaming"


class EndOfStream(Exception):
    """Sentinel sealed (as a store-level error) after the last yielded
    item — lets the consumer detect stream end with a non-deserializing
    error peek instead of fetching and decoding the value."""


class ObjectRefGenerator:
    """Iterator of ObjectRefs produced by a streaming task.  ``next``
    blocks until the next yield is sealed, then returns its ref; raises
    StopIteration on the end-of-stream sentinel.  After an error ref is
    returned the stream ends (the producer stopped there)."""

    def __init__(self, task_id: TaskID):
        self._task_id = task_id
        self._index = 0
        self._done = False

    @property
    def task_id(self) -> TaskID:
        return self._task_id

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        return self._next(timeout=None)

    def next_ready(self, timeout: Optional[float]) -> ObjectRef:
        """Like next() but bounded: raises GetTimeoutError if the
        producer hasn't sealed the next item in time."""
        return self._next(timeout=timeout)

    def _next(self, timeout: Optional[float]) -> ObjectRef:
        from ray_tpu.core import api
        from ray_tpu.core.exceptions import GetTimeoutError

        if self._done:
            raise StopIteration
        store = api.runtime().store
        oid = ObjectID.for_task_return(self._task_id, self._index)
        # Wait for the seal without deserializing the value (the
        # consumer's ray.get does the one and only decode).
        ready, _ = store.wait([oid], 1, timeout)
        if not ready:
            raise GetTimeoutError(
                f"stream item {self._index} not produced within {timeout}s"
            )
        err = store.peek_error(oid)
        if isinstance(err, EndOfStream):
            self._done = True
            raise StopIteration
        if err is not None:
            # Producer errored at this index: surface the ref (its get
            # raises the error) and end the stream.
            self._done = True
        self._index += 1
        return ObjectRef(oid)

    def __del__(self):
        # GC of the consumer handle releases sealed-but-unconsumed
        # stream items (and the end-of-stream sentinel) — consumed items
        # have their own counted ObjectRef handles (parity: the
        # streaming generator's out-of-scope cleanup in task_manager.cc).
        try:
            from ray_tpu.core import api

            if api.is_initialized():
                rt = api.runtime()
                # Async: __del__ may run inside a GC pause on a thread
                # holding store/wire locks — never do lock-taking (or
                # RPC) work here.
                release = getattr(rt, "release_stream_async", None)
                if release is not None:
                    release(self._task_id, self._index)
        except Exception:
            pass

    def __repr__(self) -> str:
        return (f"ObjectRefGenerator(task={self._task_id.hex()[:12]}, "
                f"next_index={self._index})")
