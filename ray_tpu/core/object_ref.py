"""ObjectRef — the distributed future.

Parity with the reference's ObjectRef (ray: python/ray/_raylet.pyx:252
``ObjectRef``): a handle to an immutable object that may not exist yet.
Holds the binary ObjectID plus owner metadata.  ``ray_tpu.get`` resolves
it through the runtime's object store.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from ray_tpu.utils.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "_owner", "owner_hint")

    def __init__(self, object_id: ObjectID, owner_hint: str = ""):
        self.id = object_id
        self.owner_hint = owner_hint  # node/worker that owns the value

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self):
        return self.id.task_id()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Refs serialize by id — ownership bookkeeping happens in the
        # serialization hooks of the runtime (borrower registration).
        return (ObjectRef, (self.id, self.owner_hint))

    # Allow `await ref` inside async actors.
    def __await__(self):
        from ray_tpu.core import api

        def _get():
            return api.get(self)

        import asyncio

        loop = asyncio.get_event_loop()
        return loop.run_in_executor(None, _get).__await__()


class ObjectState:
    """Store-side bookkeeping for one object (local runtime)."""

    __slots__ = ("event", "value_bytes", "error", "in_band", "in_shm",
                 "shm_size", "spilled_uri", "last_access", "lost")

    def __init__(self):
        self.event = threading.Event()
        self.value_bytes: Optional[bytes] = None
        self.error: Optional[BaseException] = None
        self.in_band: Any = None
        # True after invalidate(): the primary copy was lost and a
        # reader should trigger lineage reconstruction (lazy, parity:
        # ObjectRecoveryManager recovers on fetch, not on node death).
        self.lost: bool = False
        # Spilled-to-disk location (parity: spilled_url in the object
        # directory) and LRU clock for choosing spill victims.
        self.spilled_uri: Optional[str] = None
        self.last_access: float = 0.0
        # Large objects live in the C++ shared-memory store, keyed by the
        # ObjectID bytes (parity: plasma promotion for big values).
        # Reader pins are GC-tied (shm_store.PinnedBuffer), no
        # bookkeeping here.
        self.in_shm: bool = False
        self.shm_size: int = 0
